"""Fault-injection suite: every failure mode the fault-tolerance layer
claims to survive is injected here and the recovery asserted
(docs/fault_tolerance.md is the failure matrix these tests pin down).

Checkpoint plane: a crash mid-save must leave the previous resume point
intact and verified; truncated/garbage files must be refused by digest,
with ``restart_epoch: -1`` falling back to the newest snapshot that still
verifies; resume round-trips Adam moments and the step count.

Batch-assembly plane: a SIGKILL'd shm batcher child is detected, its ring
slots reclaimed, and the child respawned — or, past the restart budget,
the pipeline degrades loudly to threaded batchers; either way batches
keep flowing within seconds and the events land in ``stats()``.

Actor plane: frame deadlines fire instead of blocking forever, one
stalled peer cannot wedge the hub for the others, a stalled entry
handshake cannot wedge later joins, and a severed gather socket makes the
worker machine rejoin through the entry port and resume episode flow with
no leaked actor thread and no learner hang on shutdown.

Fast tests run in the tier-1 sweep; the end-to-end injections are marked
``slow``.  CI runs the whole module standalone under ``-m faults``.
"""

import json
import os
import random
import signal
import socket
import threading
import time
from multiprocessing import shared_memory

import numpy as np
import pytest

import handyrl_tpu.runtime.checkpoint as cp
from handyrl_tpu.config import normalize_args
from handyrl_tpu.runtime.connection import (
    FramedConnection,
    QueueCommunicator,
    accept_socket_connections,
    connect_socket_connection,
    send_recv,
)

pytestmark = pytest.mark.faults


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tiny_args(extra=None, worker_extra=None):
    return normalize_args(
        {
            "env_args": {"env": "TicTacToe"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "minimum_episodes": 10,
                "update_episodes": 12,
                "maximum_episodes": 100,
                "epochs": 1,
                "num_batchers": 1,
                "eval_rate": 0.2,
                "worker": {"num_parallel": 2},
                **(extra or {}),
            },
            "worker_args": worker_extra or {},
        }
    )


def _params(value: float):
    return {"w": np.full((3, 3), value, np.float32)}


def _state(value: float, steps: int):
    return {"params": _params(value), "steps": np.int32(steps)}


def _seed_snapshots(model_dir, epochs=(1, 2, 3)):
    for e in epochs:
        cp.save_epoch_snapshot(model_dir, e, _params(float(e)), _state(float(e), e * 10), e * 10)


# ---------------------------------------------------------------------------
# checkpoint plane
# ---------------------------------------------------------------------------


def test_crash_mid_save_keeps_previous_resume_point(tmp_path, monkeypatch):
    """Power loss during a save (simulated: fsync raises) must leave the
    previous epoch's files byte-intact and still digest-verified."""
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1,))

    def dying_fsync(fd):
        raise OSError("simulated power loss mid-write")

    monkeypatch.setattr(os, "fsync", dying_fsync)
    with pytest.raises(OSError):
        cp.save_epoch_snapshot(d, 2, _params(2.0), _state(2.0, 20), 20)
    monkeypatch.undo()

    assert cp.latest_verified_epoch(d) == 1
    restored = cp.load_verified_params(d, 1, _params(0.0))
    np.testing.assert_array_equal(restored["w"], _params(1.0)["w"])
    # the manifest never recorded epoch 2 — a half-written file cannot
    # become a resume candidate
    assert "2" not in cp.load_manifest(d)["epochs"]


def test_stray_tmp_files_never_break_resume(tmp_path):
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1, 2))
    # a crash between mkstemp and rename leaves exactly this
    with open(os.path.join(d, "3.ckpt.tmp.abc123"), "wb") as f:
        f.write(b"partial garbage")
    assert cp.latest_verified_epoch(d) == 2
    np.testing.assert_array_equal(
        cp.load_verified_params(d, 2, _params(0.0))["w"], _params(2.0)["w"]
    )


def test_truncated_snapshot_falls_back_to_older_verified(tmp_path):
    d = str(tmp_path)
    _seed_snapshots(d)
    with open(cp.model_path(d, 3), "r+b") as f:
        f.truncate(16)
    assert cp.latest_verified_epoch(d) == 2


def test_digest_mismatch_refused_and_skipped(tmp_path):
    """Same-size bit corruption: undetectable by existence/size checks,
    caught by CRC32.  Explicit loads refuse; auto-resume skips past."""
    d = str(tmp_path)
    _seed_snapshots(d)
    blob = open(cp.model_path(d, 3), "rb").read()
    with open(cp.model_path(d, 3), "wb") as f:
        f.write(bytes([blob[0] ^ 0xFF]) + blob[1:])
    assert cp.latest_verified_epoch(d) == 2
    with pytest.raises(cp.CheckpointError):
        cp.load_verified_params(d, 3, _params(0.0))


def test_corrupt_state_detected_by_manifest(tmp_path):
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1,))
    assert cp.verify_state(d, 1) is True
    with open(os.path.join(d, "state.ckpt"), "r+b") as f:
        f.truncate(8)
    assert cp.verify_state(d, 1) is False


def test_premanifest_layout_still_loads_and_auto_resumes(tmp_path):
    """Checkpoints from before the manifest existed (or with a deleted
    manifest) must keep loading — verification only refuses files it has
    a digest for — and auto-resume must fall back to the newest on-disk
    snapshot instead of silently restarting the run from scratch."""
    d = str(tmp_path)
    cp.save_params(cp.model_path(d, 3), _params(3.0))
    cp.save_params(cp.model_path(d, 4), _params(4.0))
    assert cp.verify_snapshot(d, 4) is None
    np.testing.assert_array_equal(
        cp.load_verified_params(d, 4, _params(0.0))["w"], _params(4.0)["w"]
    )
    # restart_epoch: -1 on an upgraded pre-manifest run dir picks the
    # newest unrecorded snapshot (an explicit epoch would load it too)
    assert cp.latest_verified_epoch(d) == 4


def test_manifest_recorded_corruption_never_resurrected_by_disk_scan(tmp_path):
    """The pre-manifest fallback must not undo verification: an epoch the
    manifest records as corrupt stays refused even if it is the newest
    file on disk."""
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1, 2))
    with open(cp.model_path(d, 2), "r+b") as f:
        f.truncate(16)
    assert cp.latest_verified_epoch(d) == 1


def test_corrupt_manifest_fails_loudly_and_save_self_heals(tmp_path):
    """An unparseable MANIFEST.json means corruption is PRESENT (manifest
    writes are atomic) — verification paths must refuse rather than
    silently load unverifiable files; the save path starts a fresh
    manifest so a healthy run keeps checkpointing and self-heals."""
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1, 2))
    with open(os.path.join(d, cp.MANIFEST_NAME), "w") as f:
        f.write("{ definitely not json")
    with pytest.raises(cp.CheckpointError):
        cp.latest_verified_epoch(d)
    with pytest.raises(cp.CheckpointError):
        cp.load_verified_params(d, 2, _params(0.0))
    # saving a new snapshot rebuilds the manifest and recovery resumes
    cp.save_epoch_snapshot(d, 3, _params(3.0), _state(3.0, 30), 30)
    assert cp.latest_verified_epoch(d) == 3


def test_retention_gc_keeps_newest_k_and_prunes_manifest(tmp_path):
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1, 2, 3, 4, 5))
    removed = cp.gc_snapshots(d, 2)
    assert removed == [1, 2, 3]
    assert sorted(cp.load_manifest(d)["epochs"]) == ["4", "5"]
    assert not os.path.exists(cp.model_path(d, 1))
    assert os.path.exists(cp.model_path(d, 5))
    assert os.path.exists(os.path.join(d, "latest.ckpt"))
    assert os.path.exists(os.path.join(d, "state.ckpt"))
    # 0 = keep all
    assert cp.gc_snapshots(d, 0) == []


def test_gc_never_collects_the_newest_verified_rollback_target(tmp_path):
    """GC x sentinel-rollback interplay: when every snapshot inside the
    retention window is corrupt, the newest VERIFIED epoch — the one the
    divergence sentinel would roll back to, and auto-resume's landing
    point — is PINNED even though it falls outside ``keep_checkpoints``.
    Collecting it would turn a one-epoch rollback into a from-scratch
    restart."""
    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1, 2, 3, 4, 5))
    # the newest two (the whole keep=2 window) rot on disk; the manifest
    # still records them, so verification is what must save epoch 3
    for e in (4, 5):
        with open(cp.model_path(d, e), "r+b") as f:
            f.write(b"\xff" * 16)
    assert cp.latest_verified_epoch(d) == 3
    removed = cp.gc_snapshots(d, 2)
    # 3 is pinned; the older unverified snapshots still go
    assert removed == [1, 2]
    assert os.path.exists(cp.model_path(d, 3))
    assert "3" in cp.load_manifest(d)["epochs"]
    # the rollback target still loads verified after the GC pass
    np.testing.assert_array_equal(
        cp.load_verified_params(d, 3, _params(0.0))["w"], _params(3.0)["w"]
    )
    # healthy directory: the pin is the newest kept snapshot anyway — GC
    # behavior is unchanged (no extra survivors)
    d2 = str(tmp_path / "healthy")
    _seed_snapshots(d2, epochs=(1, 2, 3, 4, 5))
    assert cp.gc_snapshots(d2, 2) == [1, 2, 3]


def test_gc_never_collects_epochs_the_serving_tier_is_routing(tmp_path):
    """GC x flywheel interplay (the serving analogue of the rollback-target
    pin above): SERVING.json publishes which epochs the serving tier is
    ROUTING (latest / staged candidate / displaced incumbent), and the
    learner's GC pass pins them — collecting the incumbent would turn a
    quality demote into a cold resurrection-from-nothing, and collecting a
    staged candidate would fail its promotion mid-gate."""
    from handyrl_tpu.flywheel import serving_pinned_epochs, write_serving_state

    d = str(tmp_path)
    _seed_snapshots(d, epochs=(1, 2, 3, 4, 5, 6, 7))
    # the serving tier routes latest=7 with candidate 2 staged and
    # incumbent 1 retained — both far outside the keep=2 window
    write_serving_state(d, latest=7, candidate=2, incumbent=1)
    pins = serving_pinned_epochs(d)
    assert pins == {7, 2, 1}
    removed = cp.gc_snapshots(d, 2, pin=pins)
    assert removed == [3, 4, 5]
    for e in (1, 2, 6, 7):
        assert os.path.exists(cp.model_path(d, e))
    # the incumbent (the sentinel's demote/rollback target) still loads
    # verified after the GC pass
    np.testing.assert_array_equal(
        cp.load_verified_params(d, 1, _params(0.0))["w"], _params(1.0)["w"]
    )
    # no state file / torn state degrades to the empty pin set, never raises
    assert serving_pinned_epochs(str(tmp_path / "absent")) == set()


def test_resume_roundtrip_preserves_adam_moments_and_steps(tmp_path):
    """The trainer contract behind every resume test: params + Adam
    moments + step count + lr EMA round-trip bit-exactly through the
    atomic snapshot, an epoch mismatch branches with a fresh optimizer,
    and a truncated state file degrades instead of raising."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.parallel import make_mesh
    from handyrl_tpu.runtime.trainer import Trainer

    args = dict(_tiny_args()["train_args"])
    args["env"] = {"env": "TicTacToe"}
    env = make_env(args["env"])
    module = env.net()
    params = init_variables(module, env)["params"]
    mesh = make_mesh({"dp": 1})

    trainer = Trainer(args, module, params, mesh)
    trainer.state_host["steps"] = np.int32(77)
    trainer.data_cnt_ema = 123.5
    d = str(tmp_path)
    cp.save_epoch_snapshot(d, 1, trainer.params_host(), trainer.save_payload(1), 77)
    state_path = os.path.join(d, "state.ckpt")

    fresh = Trainer(args, module, params, mesh)
    assert fresh.load_state(state_path, expected_epoch=1) is True
    assert fresh.steps == 77
    assert fresh.data_cnt_ema == 123.5
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        trainer.state_host["opt_state"],
        fresh.state_host["opt_state"],
    )

    # epoch mismatch = branch, not resume
    other = Trainer(args, module, params, mesh)
    assert other.load_state(state_path, expected_epoch=2) is False

    # truncated state = fresh optimizer, never an exception
    with open(state_path, "r+b") as f:
        f.truncate(8)
    broken = Trainer(args, module, params, mesh)
    assert broken.load_state(state_path, expected_epoch=1) is False


@pytest.mark.slow
def test_learner_auto_resume_after_corruption(tmp_path, monkeypatch):
    """End to end: train 2 epochs, truncate the newest snapshot, restart
    with ``restart_epoch: -1`` — the learner resumes from epoch 1 (the
    newest VERIFIED snapshot) and keeps training."""
    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    learner = Learner(_tiny_args({"epochs": 2}))
    learner.run()
    assert learner.model_epoch == 2
    assert cp.latest_verified_epoch("models") == 2

    with open("models/2.ckpt", "r+b") as f:
        f.truncate(16)

    resumed = Learner(_tiny_args({"restart_epoch": -1, "epochs": 3}))
    assert resumed.model_epoch == 1, "auto-resume must land on the newest verified epoch"
    resumed.run()
    assert resumed.model_epoch == 3
    # the re-written epoch snapshots verify again
    assert cp.latest_verified_epoch("models") == 3


# ---------------------------------------------------------------------------
# batch-assembly plane
# ---------------------------------------------------------------------------


def _gen_store(n, targs, seed=0):
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.runtime.generation import Generator
    from handyrl_tpu.runtime.replay import EpisodeStore

    random.seed(seed)
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env, seed=seed))
    gen = Generator(env, targs)
    models = {p: model for p in env.players()}
    gen_args = {"player": env.players(), "model_id": {p: 1 for p in env.players()}}
    store = EpisodeStore(1000)
    eps = []
    while len(eps) < n:
        ep = gen.generate(models, gen_args)
        if ep is not None:
            eps.append(ep)
    store.extend(eps)
    return store


class _HostCtx:
    """put_batch stub (mirrors tests/test_shm_pipeline.py)."""

    def put_batch(self, batch):
        import jax

        return jax.tree.map(np.array, batch)

    def put_batches(self, batches):
        import jax

        return [jax.tree.map(np.array, b) for b in batches]


def _shm_args(**over):
    raw = {"env_args": {"env": "TicTacToe"}, "train_args": over}
    return normalize_args(raw)["train_args"]


def test_sigkilled_batcher_child_is_respawned_and_batches_flow():
    """Acceptance: SIGKILL one shm batcher child mid-run -> batch flow
    resumes within 10 s, the death and respawn are visible in stats."""
    from handyrl_tpu.runtime.shm_batch import ShmBatchPipeline

    targs = _shm_args(batch_size=4, forward_steps=8, num_batchers=2,
                      batcher_max_restarts=3, batcher_stall_timeout=30.0)
    store = _gen_store(8, targs)
    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    try:
        assert pipe._fallback is None, "shm plane fell back before the injection"
        assert pipe.batch() is not None  # steady state reached

        victim = pipe._procs[0]
        os.kill(victim.pid, signal.SIGKILL)

        # flow must resume: drain well past every pre-kill buffer (device
        # queue depth 2 + up to n_slots filled slots) inside the 10 s SLO
        deadline = time.monotonic() + 10.0
        drained = 0
        while drained < 10 and time.monotonic() < deadline:
            assert pipe.batch() is not None, "pipeline died after child SIGKILL"
            drained += 1
        assert drained >= 10, f"only {drained} batches within 10s of the SIGKILL"

        # supervision notices within the same SLO (the drain above can
        # finish in well under one 0.25s supervision tick)
        while time.monotonic() < deadline:
            if pipe.stats()["batcher_deaths"] >= 1:
                break
            pipe.batch()  # keep the ring moving
            time.sleep(0.05)
        stats = pipe.stats()
        assert stats["batcher_deaths"] >= 1, "supervision missed the dead child"
        assert stats["batcher_restarts"] >= 1 or stats.get("batcher_fallback"), (
            "dead child neither respawned nor degraded"
        )
        # the respawned child is actually alive
        if not stats.get("batcher_fallback"):
            alive = [p for p in pipe._procs if p is not None and p.is_alive()]
            assert len(alive) == 2, "respawn did not restore the child pool"
    finally:
        stop.set()
        pipe.stop()
    for proc in pipe._procs:
        if proc is not None:
            proc.join(timeout=5)
            assert not proc.is_alive(), "orphaned batcher process"


def test_batcher_restart_budget_degrades_to_thread_pipeline():
    """Past ``batcher_max_restarts`` the shm plane must hand over to the
    threaded pipeline loudly — batches keep flowing, the mode flips, the
    shm segment is unlinked."""
    from handyrl_tpu.runtime.shm_batch import ShmBatchPipeline

    targs = _shm_args(batch_size=4, forward_steps=8, num_batchers=1,
                      batcher_max_restarts=0, batcher_stall_timeout=30.0)
    store = _gen_store(8, targs)
    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    shm_name = pipe._shm.name
    try:
        assert pipe._fallback is None
        assert pipe.batch() is not None
        os.kill(pipe._procs[0].pid, signal.SIGKILL)

        # batches may keep draining from pre-kill buffers while supervision
        # notices the death (throttled ticks); poll for the mode flip, then
        # prove continued flow THROUGH the fallback
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if pipe.stats()["mode"] == "thread":
                break
            assert pipe.batch() is not None, "no batches after the kill"
            time.sleep(0.05)
        stats = pipe.stats()
        assert stats["mode"] == "thread", "stats must expose the degraded mode"
        assert stats["batcher_deaths"] >= 1
        assert stats["batcher_fallback"] == 1.0
        for _ in range(3):
            assert pipe.batch() is not None, "fallback pipeline not producing"

        # the shm ring is fully torn down behind the fallback
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                probe = shared_memory.SharedMemory(name=shm_name)
                probe.close()
                time.sleep(0.2)
            except FileNotFoundError:
                break
        else:
            pytest.fail("shm segment still linked after degradation")
    finally:
        stop.set()
        pipe.stop()


# ---------------------------------------------------------------------------
# actor plane
# ---------------------------------------------------------------------------


def test_framed_recv_deadline_fires():
    port = free_port()

    def silent_server():
        for conn in accept_socket_connections(port=port, maxsize=1):
            time.sleep(2.0)  # accept, then say nothing
            conn.close()

    t = threading.Thread(target=silent_server, daemon=True)
    t.start()
    conn = connect_socket_connection("localhost", port, retry_seconds=5.0)
    t0 = time.monotonic()
    with pytest.raises(socket.timeout):
        conn.recv(timeout=0.3)
    assert time.monotonic() - t0 < 1.5
    conn.close()


def test_stalled_peer_does_not_wedge_other_peers():
    """One peer that stops reading (TCP window + its bounded send queue
    fill up) must be disconnected while the hub keeps serving everyone
    else — the single-shared-send-loop design this replaces wedged ALL
    peers on one stalled sendall."""
    port = free_port()
    hub = QueueCommunicator(send_queue_size=2)
    ready = threading.Event()
    ids = {}

    def server():
        for conn in accept_socket_connections(port=port, maxsize=2):
            hub.add_connection(conn)
        # learn which conn is which from a hello frame
        for _ in range(2):
            conn, data = hub.recv(timeout=10)
            ids[data] = conn
        ready.set()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    stalled = connect_socket_connection("localhost", port, retry_seconds=5.0)
    healthy = connect_socket_connection("localhost", port, retry_seconds=5.0)
    stalled.send("stalled")
    healthy.send("healthy")
    assert ready.wait(timeout=10)

    # flood the stalled peer (which never reads) until its queue overflows
    big = np.zeros((1 << 18,), np.uint8)  # 256 KiB frames
    for _ in range(200):
        hub.send(ids["stalled"], big)
        if hub.connection_count() <= 1:
            break
    deadline = time.monotonic() + 10.0
    while hub.connection_count() > 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert hub.connection_count() == 1, "stalled peer was never torn down"

    # ...and the healthy peer is still served promptly
    hub.send(ids["healthy"], ("pong", 42))
    assert healthy.recv(timeout=5.0) == ("pong", 42)
    healthy.close()
    stalled.close()
    hub.shutdown()


def test_stalled_entry_handshake_does_not_block_joins():
    """Satellite: the entry thread recv()s with a HARD deadline — a
    client that connects and sends nothing, or drip-feeds one byte per
    gap (which a mere silence bound would keep alive forever), is
    dropped, and a well-behaved join right behind it completes."""
    from handyrl_tpu.runtime.server import WorkerServer

    entry_port, data_port = free_port(), free_port()
    args = {
        "env": {"env": "TicTacToe"},
        "worker": {
            "num_parallel": 2,
            "entry_port": entry_port,
            "data_port": data_port,
            "entry_timeout": 1.0,
            "heartbeat_interval": 0,
        },
    }
    server = WorkerServer(args, lambda req, data, timeout=None: None, None)
    server.run()
    try:
        trickler = socket.create_connection(("localhost", entry_port), timeout=5)
        stop_trickle = threading.Event()

        def trickle():
            # a huge frame length, then one byte every 0.4s (< the 1.0s
            # entry_timeout, so only an ABSOLUTE budget can shed it)
            try:
                trickler.sendall(b"\x00\xff\xff\xff")
                while not stop_trickle.is_set():
                    trickler.sendall(b"x")
                    stop_trickle.wait(0.4)
            except OSError:
                pass  # server dropped us: the desired outcome

        threading.Thread(target=trickle, daemon=True).start()
        time.sleep(0.2)  # ensure the trickler is accepted first
        conn = connect_socket_connection("localhost", entry_port, retry_seconds=5.0)
        t0 = time.monotonic()
        reply = send_recv(conn, {"num_parallel": 2}, timeout=10.0)
        elapsed = time.monotonic() - t0
        assert reply["worker_args"]["base_worker_id"] == 0
        assert reply["env_args"] == {"env": "TicTacToe"}
        assert elapsed < 8.0, f"join waited {elapsed:.1f}s behind a trickled handshake"
        conn.close()
        stop_trickle.set()
        trickler.close()
    finally:
        server.shutdown_flag = True


@pytest.mark.slow
def test_severed_gather_socket_rejoins_and_training_finishes(tmp_path, monkeypatch):
    """Acceptance: sever every gather connection mid-run — the worker
    machine tears its session down (no actor thread survives it), rejoins
    through the entry port with backoff, episode flow resumes, training
    finishes every epoch, and shutdown drains cleanly."""
    from handyrl_tpu.runtime.learner import Learner
    from handyrl_tpu.runtime.server import RemoteWorkerCluster

    monkeypatch.chdir(tmp_path)
    entry_port, data_port = free_port(), free_port()
    args = _tiny_args(
        {
            "epochs": 3,
            "maximum_episodes": 200,
            "mesh": {"dp": 1},  # transport test, not a sharding test
            "worker": {
                "num_parallel": 2,
                "entry_port": entry_port,
                "data_port": data_port,
                "heartbeat_interval": 1.0,
                "socket_timeout": 15.0,
                "entry_timeout": 5.0,
            },
        },
        worker_extra={
            "server_address": "localhost",
            "num_parallel": 2,
            "entry_port": entry_port,
            "rejoin_backoff": 0.2,
            "rejoin_backoff_max": 1.0,
            "max_rejoins": 20,
            "entry_retry_seconds": 2.0,
        },
    )

    learner = Learner(args, remote=True)
    learner_thread = threading.Thread(target=learner.run, daemon=True)
    learner_thread.start()

    cluster = RemoteWorkerCluster(args["worker_args"])
    cluster_thread = threading.Thread(target=cluster.run, daemon=True)
    cluster_thread.start()

    # let the machine join and deliver, then cut every data connection
    deadline = time.time() + 120
    while learner.num_returned_episodes < 4 and time.time() < deadline:
        time.sleep(0.2)
    assert learner.num_returned_episodes >= 4, "worker machine never delivered"
    episodes_before = learner.num_returned_episodes
    severed = learner.worker.connections()
    assert severed, "no gather connections to sever"
    for conn in severed:
        learner.worker.disconnect(conn)

    learner_thread.join(timeout=420)
    assert not learner_thread.is_alive(), "learner hung after the severed socket"
    assert learner.num_returned_episodes > episodes_before, (
        "episode flow never recovered after the rejoin"
    )
    assert os.path.exists("models/3.ckpt")
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) >= 3

    # the cluster exits its supervision loop on the clean drain...
    cluster_thread.join(timeout=60)
    assert not cluster_thread.is_alive(), "worker cluster never exited after drain"
    # ...and no actor thread from ANY session (severed or final) leaks
    leaked = [
        t for t in threading.enumerate()
        if t.name.startswith("remote-actor-") and t.is_alive()
    ]
    assert not leaked, f"leaked actor threads: {[t.name for t in leaked]}"
