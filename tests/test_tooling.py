"""Offline tooling tests: StableHLO export, SWA averaging, log plotters.

Parity surface: reference scripts/ (aux_swa.py, make_onnx_model.py,
win_rate/loss/stats plotters) per SURVEY.md §2.3.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Environmental, reproduces at the seed commit on this container's jax
# 0.4.37: models/export.py drives ``jax.export`` (symbolic_shape /
# SymbolicScope / export / deserialize), which this jax exposes only as
# ``jax.experimental.export`` — ``AttributeError: module 'jax' has no
# attribute 'export'`` before any model code runs.  Skip (not fail) where
# the public module is absent.
needs_jax_export = pytest.mark.skipif(
    not hasattr(jax, "export"),
    reason="jax.export unavailable on this jax (< 0.5); StableHLO export "
    "tooling needs it (seed-reproducing environmental failure)",
)


def _model(env_name):
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables

    env = make_env({"env": env_name})
    module = env.net()
    variables = init_variables(module, env)
    return env, module, variables, InferenceModel(module, variables)


@needs_jax_export
@pytest.mark.parametrize("env_name", ["TicTacToe", "Geister"])
def test_export_roundtrip(env_name, tmp_path):
    from handyrl_tpu.models import ExportedModel, export_model
    from handyrl_tpu.utils import tree_stack

    env, module, variables, model = _model(env_name)
    env.reset()
    obs = env.observation(env.players()[0])
    path = str(tmp_path / f"{env_name}.hlo")
    export_model(module, variables, obs, path)

    ex = ExportedModel(path)
    o1 = model.inference(obs, model.init_hidden())
    o2 = ex.inference(obs, ex.init_hidden())
    np.testing.assert_allclose(o1["policy"], o2["policy"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o1["value"], o2["value"], rtol=1e-4, atol=1e-5)

    # dynamic batch dimension: batch-3 through the same artifact
    obs_b = tree_stack([obs, obs, obs])
    hidden = ex.init_hidden()
    hidden_b = None if hidden is None else tree_stack([hidden] * 3)
    out = ex.inference_batch(obs_b, hidden_b)
    assert np.asarray(out["policy"]).shape[0] == 3


@needs_jax_export
def test_exported_model_plays_matches(tmp_path):
    from handyrl_tpu.runtime.evaluation import exec_match, load_model_agent
    from handyrl_tpu.agents import RandomAgent
    from handyrl_tpu.models import export_model

    env, module, variables, model = _model("TicTacToe")
    env.reset()
    path = str(tmp_path / "ttt.hlo")
    export_model(module, variables, env.observation(0), path)

    agents = {0: load_model_agent(path, env), 1: RandomAgent()}
    outcome = exec_match(env, agents)
    assert outcome is not None and set(outcome) == {0, 1}


def test_swa_script(tmp_path):
    from handyrl_tpu.runtime.checkpoint import load_params, model_path, save_params
    from handyrl_tpu.utils import tree_map

    env, module, variables, model = _model("TicTacToe")
    model_dir = tmp_path / "models"
    base = variables["params"]
    for epoch, scale in ((1, 1.0), (2, 2.0), (3, 3.0)):
        save_params(str(model_path(str(model_dir), epoch)), tree_map(lambda x: np.asarray(x) * scale, base))

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "aux_swa.py"), str(model_dir), "3", "3"],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
    )
    assert proc.returncode == 0, proc.stderr
    swa = load_params(str(model_dir / "swa.ckpt"), base)
    # average of 1x, 2x, 3x = 2x
    np.testing.assert_allclose(
        np.asarray(next(iter(jax_leaves(swa)))),
        np.asarray(next(iter(jax_leaves(base)))) * 2.0,
        rtol=1e-5,
    )


def jax_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_logparse_both_formats(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from _logparse import parse_records

    metrics = tmp_path / "metrics.jsonl"
    with open(metrics, "w") as f:
        for e in range(3):
            f.write(json.dumps({"epoch": e, "win_rate": {"total": 0.5 + 0.1 * e},
                                "loss": {"p": 0.4 - 0.1 * e, "v": 0.3},
                                "generation_mean": 0.0, "generation_std": 0.9}) + "\n")
    recs = parse_records(str(metrics))
    assert len(recs) == 3 and recs[2]["win_rate"]["total"] == 0.7

    log = tmp_path / "train.log"
    log.write_text(
        "started server\n"
        "epoch 0\n"
        "win rate = 0.520 (13.0 / 25)\n"
        "generation stats = 0.100 +- 0.935\n"
        "loss = ent:1.418 p:0.375 r:0.000 total:0.590 v:0.311\n"
        "updated model(1)\n"
        "epoch 1\n"
        "win rate (random) = 0.769 (10.0 / 13)\n"
        "generation stats = 0.200 +- 0.866\n"
        "loss = ent:1.453 p:0.354 r:0.000 total:0.531 v:0.273\n"
        "updated model(331)\n"
    )
    recs = parse_records(str(log))
    assert len(recs) == 2
    assert recs[0]["win_rate"]["total"] == 0.520
    assert recs[1]["win_rate"]["random"] == 0.769
    assert recs[1]["loss"]["p"] == 0.354
    assert recs[1]["steps"] == 331
    assert recs[0]["generation_mean"] == 0.1


@pytest.mark.parametrize("script", ["win_rate_plot.py", "loss_plot.py", "stats_plot.py"])
def test_plot_scripts(script, tmp_path):
    metrics = tmp_path / "metrics.jsonl"
    with open(metrics, "w") as f:
        for e in range(5):
            f.write(json.dumps({"epoch": e, "win_rate": {"total": 0.5, "random": 0.6},
                                "loss": {"p": 0.4, "v": 0.3, "total": 0.7},
                                "generation_mean": 0.1 * e, "generation_std": 0.5}) + "\n")
    out = tmp_path / "plot.png"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", script), str(metrics), str(out)],
        capture_output=True,
        text=True,
        env={**os.environ, "PYTHONPATH": REPO, "MPLBACKEND": "Agg"},
    )
    assert proc.returncode == 0, proc.stderr
    assert out.exists() and out.stat().st_size > 1000


@pytest.mark.parametrize("env_name", ["TicTacToe", "Geister"])
def test_savedmodel_roundtrip(env_name, tmp_path):
    """jax2tf SavedModel bridge: outputs (incl. recurrent hidden) match the
    live model, and the batch dimension stays polymorphic."""
    pytest.importorskip("tensorflow")
    from handyrl_tpu.models.export import SavedModelModel, export_savedmodel
    from handyrl_tpu.utils import tree_map, tree_stack

    env, module, variables, model = _model(env_name)
    env.reset()
    obs = env.observation(env.players()[0])
    path = str(tmp_path / f"{env_name}.tf")
    export_savedmodel(module, variables, obs, path)

    sm = SavedModelModel(path)
    o1 = model.inference(obs, model.init_hidden())
    o2 = sm.inference(obs, sm.init_hidden())
    np.testing.assert_allclose(o1["policy"], o2["policy"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o1["value"], o2["value"], rtol=1e-4, atol=1e-5)
    if o1.get("hidden") is not None:
        for a, b in zip(
            jax.tree.leaves(o1["hidden"]), jax.tree.leaves(o2["hidden"])
        ):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    obs_b = tree_stack([obs, obs, obs])
    hidden = sm.init_hidden()
    hidden_b = None if hidden is None else tree_stack([hidden] * 3)
    out = sm.inference_batch(obs_b, hidden_b)
    assert np.asarray(out["policy"]).shape[0] == 3


@pytest.mark.parametrize("env_name", ["TicTacToe", "Geister"])
def test_onnx_roundtrip(env_name, tmp_path):
    """Real .onnx artifact (jaxpr -> torch bridge, models/torch_export.py)
    loaded through onnxruntime matches the live model — the reference's
    exact deployment path (scripts/make_onnx_model.py:28-58,
    evaluation.py:287-353).  The EXPORT side runs and is verified
    in-image (tests/test_export_onnx_contract.py); onnxruntime execution
    is what needs the optional dep, so this skips where it is absent —
    except in the CI extras job (HANDYRL_REQUIRE_EXTRAS), which exists to
    execute this leg and must FAIL loudly on a missing/broken dep."""
    if os.environ.get("HANDYRL_REQUIRE_EXTRAS"):
        import onnxruntime  # noqa: F401
        import torch  # noqa: F401
    else:
        pytest.importorskip("torch")  # the export side runs on torch
        pytest.importorskip("onnxruntime")
    from handyrl_tpu.models.export import OnnxModel, export_onnx

    env, module, variables, model = _model(env_name)
    env.reset()
    obs = env.observation(env.players()[0])
    path = str(tmp_path / f"{env_name}.onnx")
    export_onnx(module, variables, obs, path)

    om = OnnxModel(path)
    o1 = model.inference(obs, model.init_hidden())
    o2 = om.inference(obs, om.init_hidden())
    np.testing.assert_allclose(o1["policy"], o2["policy"], rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(o1["value"], o2["value"], rtol=1e-3, atol=1e-4)
    if o1.get("hidden") is not None:
        for a, b in zip(
            jax.tree.leaves(o1["hidden"]), jax.tree.leaves(o2["hidden"])
        ):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)
