"""On-device evaluation (runtime/device_eval.py) tests.

The claims: the device rule-based twin picks the SAME move as the host
greedy food-seeker wherever the host is deterministic; the evaluator's
outcome counts are exact and feed the learner's win-rate books; and a
learner run with ``device_eval_games`` records a dense per-epoch curve
(the starvation this module exists to fix).
"""

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.envs.vector_hungry_geese import (
    MAXLEN,
    VectorHungryGeese,
)
from handyrl_tpu.models import init_variables
from handyrl_tpu.runtime.device_eval import DeviceEvaluator, build_eval_stream_fn


def _host_view(state, lane):
    """Rebuild the host env fields (geese bodies, food, last_actions) from
    one lane of the fetched vector state."""
    cells = np.asarray(state["cells"])[lane]
    head_ptr = np.asarray(state["head_ptr"])[lane]
    length = np.asarray(state["length"])[lane]
    food = list(np.flatnonzero(np.asarray(state["food"])[lane]))
    last = np.asarray(state["last_action"])[lane]
    geese = []
    for p in range(VectorHungryGeese.num_players):
        body = [
            int(cells[p][(head_ptr[p] + i) % MAXLEN]) for i in range(length[p])
        ]
        geese.append(body)
    last_actions = {p: int(last[p]) for p in range(len(last)) if last[p] >= 0}
    return geese, food, last_actions


def test_rulebase_device_twin_matches_host():
    """Wherever the host greedy agent is deterministic (not boxed in), the
    device twin must pick the identical direction."""
    key = jax.random.PRNGKey(0)
    state = VectorHungryGeese.init(16, key)
    env = make_env({"env": "HungryGeese"})
    checked = 0
    for it in range(12):
        key, ka, kr, kf = jax.random.split(key, 4)
        dev = np.asarray(VectorHungryGeese.rule_based_action_all(state, kr))
        host_state = jax.device_get(state)
        active = np.asarray(host_state["active"])
        for lane in range(active.shape[0]):
            geese, food, last_actions = _host_view(host_state, lane)
            env.geese = geese
            env.food = food
            env.last_actions = last_actions
            blocked = {c for g in geese for c in g}
            for p in range(VectorHungryGeese.num_players):
                if not active[lane, p] or not geese[p]:
                    continue
                # skip the host's random boxed-in branch
                from handyrl_tpu.envs.hungry_geese import _OPPOSITE, _translate

                last = last_actions.get(p)
                valid = [
                    d for d in range(4)
                    if (last is None or d != _OPPOSITE[last])
                    and _translate(geese[p][0], d) not in blocked
                ]
                if not valid:
                    continue
                assert dev[lane, p] == env.rule_based_action(p), (
                    f"iter {it} lane {lane} player {p}"
                )
                checked += 1
        # advance every lane with random legal actions
        actions = jax.random.randint(
            ka, (16, VectorHungryGeese.num_players), 0, 4
        )
        state = VectorHungryGeese.reset_done(state, kf)
        state = VectorHungryGeese.step(state, actions, kf)
    assert checked > 200, f"only {checked} deterministic decisions compared"


def test_device_evaluator_counts_and_balance():
    """Exact outcome counting over >= num_games finished matches, outcomes
    on the rank ladder, seats round-robin."""
    env = make_env({"env": "HungryGeese"})
    module = env.net()
    params = init_variables(module, env)["params"]
    ev = DeviceEvaluator(VectorHungryGeese, module, n_lanes=16,
                         opponent="rulebase")
    counts = ev.evaluate(params, 40, jax.random.PRNGKey(1))
    games = sum(counts.values())
    assert games >= 40
    for o in counts:
        assert -1.0 <= o <= 1.0
    seats = np.asarray(ev._net_seat)
    assert sorted(set(seats.tolist())) == [0, 1, 2, 3]


def test_device_evaluator_geister_recurrent():
    """The same evaluator drives turn-based + recurrent envs: Geister's
    DRC net vs legal-masked random, hidden advancing for both seats every
    step (the host Agent's observation=True behavior)."""
    from handyrl_tpu.envs.vector_geister import VectorGeister

    env = make_env({"env": "Geister"})
    module = env.net()
    params = init_variables(module, env)["params"]
    ev = DeviceEvaluator(VectorGeister, module, n_lanes=8, opponent="random",
                         k_steps=64)
    counts = ev.evaluate(params, 8, jax.random.PRNGKey(2), max_calls=8)
    games = sum(counts.values())
    assert games >= 8
    assert all(o in (-1.0, 0.0, 1.0) for o in counts), counts


def test_eval_stream_fn_rejects_unknown_opponent():
    env = make_env({"env": "HungryGeese"})
    module = env.net()
    with pytest.raises(ValueError):
        build_eval_stream_fn(VectorHungryGeese, module, 8, 8, opponent="self")


def test_learner_device_eval_rejects_episodic_twin(tmp_path, monkeypatch):
    """device_eval_games with an episodic vector env (no streaming
    reset_done/step hooks — VectorTicTacToe, the Connect Four example)
    must fail at Learner construction with the limitation named, not
    AttributeError inside the eval thread at the first epoch boundary."""
    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "batch_size": 8,
            "forward_steps": 8,
            "epochs": 1,
            "eval_rate": 0.0,
            "device_rollout_games": 8,
            "device_eval_games": 8,
            "worker": {"num_parallel": 1},
        },
    })
    with pytest.raises(ValueError, match="episodic"):
        Learner(cfg)


@pytest.mark.slow  # heaviest single test in the fast tier (~47s of
# compiles on 1 CPU core); the slow CI leg keeps it green
def test_learner_device_eval_records_curve(tmp_path, monkeypatch):
    """A device_replay run with device_eval_games must record a win_rate
    entry EVERY epoch — the host-worker curve starves on slow hosts (the
    round-3 soaks' NaN curves), the device curve must not."""
    import json

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 8,
            "forward_steps": 8,
            "minimum_episodes": 10,
            "update_episodes": 40,
            "maximum_episodes": 1000,
            "epochs": 2,
            "eval_rate": 0.0,
            "device_rollout_games": 8,
            "device_replay": True,
            "device_replay_slots": 256,
            "device_replay_k_steps": 16,
            "device_eval_games": 8,
            "eval": {"opponent": ["rulebase"]},
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(cfg)
    assert learner._device_eval is not None
    assert learner._device_eval.opponent == "rulebase"
    learner.run()

    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) == 2
    for r in records:
        assert "win_rate" in r, f"epoch {r['epoch']} has no win rate"
