"""Multi-host learner plane: jax.distributed over two localhost processes.

The reference has no multi-host learner at all (nn.DataParallel is
single-process, reference train.py:340-341); SURVEY.md §2.5 prescribes
jax.distributed + XLA collectives for the gradient plane.  This test runs
TWO real OS processes, each with 2 virtual CPU devices, connected through
``init_distributed`` — the global mesh spans 4 devices — and checks:

* a dp-sharded global array assembled from per-process local shards
  (``TrainContext.put_batch``'s multi-process path) reduces correctly
  through a jitted collective;
* only the coordinator (process 0) passes the checkpoint/metrics guard.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

# the whole multi-process surface runs in its own 2-process CI steps
# (fast leg: epoch loop + resume broadcast + init timeout; slow leg: the
# host-loss / coordinator-death e2es), excluded from the general legs
pytestmark = pytest.mark.multihost

_CHILD = r"""
import json, os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from handyrl_tpu.parallel import (
    init_distributed,
    is_coordinator,
    local_batch_size,
    make_mesh,
)

rank = init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)
assert rank == pid, (rank, pid)
assert jax.process_count() == nproc
assert len(jax.devices()) == 2 * nproc  # global device view

mesh = make_mesh({"dp": -1})
sharding = NamedSharding(mesh, PartitionSpec("dp"))

# per-process local shard of a global batch: process p contributes rows p+1
B_local = local_batch_size(4)
local = np.full((B_local, 3), pid + 1.0, np.float32)
arr = jax.make_array_from_process_local_data(sharding, local)
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)

# put_batches' multi-process branch (fused_steps path): stack k local
# batch shards -> (k, B, ...) global tree, reduce through a collective
from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.parallel import TrainContext

cfg = normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": {"batch_size": 4}})
targs = dict(cfg["train_args"]); targs["env"] = cfg["env_args"]
ctx = TrainContext(make_env(cfg["env_args"]).net(), targs, mesh)
host_batches = [
    {"action": np.full((B_local, 1), pid + 1.0, np.float32)} for _ in range(3)
]
stacked = ctx.put_batches(host_batches)
ssum = jax.jit(
    lambda t: t["action"].sum(), out_shardings=NamedSharding(mesh, PartitionSpec())
)(stacked)
# 3 stacked batches x (2 local rows x 1 col) x (1 + 2) across processes
assert abs(float(ssum) - 18.0) < 1e-6, float(ssum)

# the checkpoint/metrics guard: exactly one writer
if is_coordinator():
    with open(os.path.join(outdir, "result.json"), "w") as f:
        json.dump({"total": float(total), "process_count": jax.process_count()}, f)
else:
    with open(os.path.join(outdir, f"noncoord_{pid}.txt"), "w") as f:
        f.write("guarded")
"""


# The gradient plane's core claim (SURVEY §2.5): TrainContext.train_step
# — value_and_grad + the GSPMD gradient all-reduce — executed ACROSS
# processes on per-process local batch shards must produce the same
# params on every process, and the same update a single process computes
# from the full batch.  Both processes seed identically, generate the
# SAME episodes/windows via the real generator, then feed only their own
# rows through put_batch's make_array_from_process_local_data branch.
_TRAIN_CHILD = r"""
import json, os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
mesh_spec = json.loads(sys.argv[5]) if len(sys.argv) > 5 else {"dp": -1}
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from handyrl_tpu.parallel import init_distributed, is_coordinator, make_mesh

init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)

sys.path.insert(0, os.getcwd())  # parent sets cwd to the tests dir
from test_multihost import build_ttt_batch, run_one_train_step

batch, module, params, args = build_ttt_batch()
mesh = make_mesh(mesh_spec)
B_local = batch["action"].shape[0] // nproc
local = jax.tree.map(lambda x: x[pid * B_local:(pid + 1) * B_local], batch)
new_params, loss = run_one_train_step(module, args, mesh, params, local)

leaves = [np.asarray(x) for x in jax.tree.leaves(new_params)]
np.savez(os.path.join(outdir, f"params_{pid}.npz"), loss=loss, *leaves)
"""


# Sequence-parallel plane across processes: masked ring attention with T
# sharded over an 'sp' axis spanning the 2-process global mesh — the K/V
# ring's ppermute hops cross process boundaries.  Inputs are seeded
# identically everywhere; each process contributes its local T rows via
# make_array_from_process_local_data, and the sharded output is
# all-gathered and dumped for comparison against the single-process
# einsum reference.
_RING_CHILD = r"""
import os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from handyrl_tpu.ops import masked_ring_self_attention
from handyrl_tpu.parallel import init_distributed, make_mesh

init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)

sys.path.insert(0, os.getcwd())
from test_multihost import build_ring_inputs

q, k, v, key_mask, slopes, window = build_ring_inputs()
mesh = make_mesh({"sp": -1})
T = q.shape[1]
T_proc = T // nproc

def put(x, spec):
    sh = NamedSharding(mesh, spec)
    local = x[:, pid * T_proc:(pid + 1) * T_proc]
    return jax.make_array_from_process_local_data(sh, np.asarray(local))

qg = put(q, P(None, "sp", None, None))
kg = put(k, P(None, "sp", None, None))
vg = put(v, P(None, "sp", None, None))
mg = put(key_mask, P(None, "sp"))

out = masked_ring_self_attention(qg, kg, vg, mg, jax.numpy.asarray(slopes), mesh, window=window)
rep = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))(out)
np.savez(os.path.join(outdir, f"ring_{pid}.npz"), out=np.asarray(jax.device_get(rep)))
"""


def build_ring_inputs():
    """Deterministic (q, k, v, key_mask, slopes, window) for the ring test —
    same values in every process (fixed PRNG keys, host numpy)."""
    import numpy as np

    rng = np.random.RandomState(99)
    B, T, H, D = 2, 32, 2, 8
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)
    key_mask = (rng.rand(B, T) < 0.7).astype(np.float32)
    slopes = (2.0 ** -np.arange(1, H + 1)).astype(np.float32)
    return q, k, v, key_mask, slopes, 8


@pytest.mark.slow
def test_two_process_ring_attention(tmp_path):
    """Masked ring attention with the 'sp' axis spanning 2 processes must
    match the single-process einsum reference — the sequence-parallel
    plane's cross-host claim (its ppermute ring hops process boundaries)."""
    import numpy as np

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RING_CHILD, str(port), str(pid), "2", str(tmp_path)],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = [p.communicate(timeout=300)[0].decode(errors="replace") for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    import jax

    jax.config.update("jax_platforms", "cpu")
    from handyrl_tpu.ops.flash_attention import masked_attention_reference

    q, k, v, key_mask, slopes, window = build_ring_inputs()
    ref = np.asarray(
        masked_attention_reference(q, k, v, key_mask, slopes, window=window)
    )
    for pid in range(2):
        got = np.load(tmp_path / f"ring_{pid}.npz")["out"]
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)


def build_ttt_batch():
    """Deterministic TicTacToe batch + module + init params (seeded global
    RNGs: every caller that seeds the same way gets byte-identical data)."""
    import random as pyrandom

    import numpy as np

    pyrandom.seed(1234)
    np.random.seed(1234)

    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, RandomModel, init_variables
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    cfg = normalize_args(
        # compaction off: the multi-process path skips it by design (all
        # processes must agree on global shapes), so the single-process
        # reference run must train the same uncompacted program
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"batch_size": 4, "compact_padding": False}}
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 8:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(
            args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
        )
        if w is not None:
            windows.append(w)
    return make_batch(windows, args), module, variables["params"], args


def run_one_train_step(module, args, mesh, params, local_batch):
    """One real TrainContext.train_step; returns (host params, loss).

    Params are re-laid-out replicated before the host fetch: under an
    'mp' mesh axis the updated kernels are SHARDED across the global
    devices, and in a multi-process run device_get of a partially
    non-addressable array fails — the jitted identity performs the
    all-gather (a no-op when already replicated)."""
    import jax
    import numpy as np

    from handyrl_tpu.parallel import TrainContext

    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(params)
    device_batch = ctx.put_batch(local_batch)
    state, metrics = ctx.train_step(state, device_batch, 1e-3)
    gathered = jax.jit(lambda t: t, out_shardings=ctx._replicated)(state["params"])
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), gathered)
    return host, float(jax.device_get(metrics["total"]))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cpu_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(port), str(pid), "2", str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    result = json.load(open(tmp_path / "result.json"))
    assert result["process_count"] == 2
    # global sum: 2 local rows x 3 cols of (pid+1) per process = 6*1 + 6*2
    assert abs(result["total"] - 18.0) < 1e-6
    assert (tmp_path / "noncoord_1.txt").exists()
    assert not (tmp_path / "noncoord_0.txt").exists()


def _two_process_train_and_compare(tmp_path, mesh_spec: str, exact_cross: bool):
    """Spawn 2 jax.distributed processes x 2 virtual devices running the
    REAL jitted sharded update on local batch shards under ``mesh_spec``,
    then assert (a) both processes end with the same params and (b) those
    params match a single-process update on the full batch (same math up
    to float reassociation — the sharded program's reduction order may
    differ, so the cross-process check is exact only for the replicated
    dp layout)."""
    import json

    import numpy as np

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _TRAIN_CHILD, str(port), str(pid), "2",
             str(tmp_path), mesh_spec],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    dumps = [np.load(tmp_path / f"params_{pid}.npz") for pid in range(2)]
    keys = sorted(
        (k for k in dumps[0].files if k != "loss"),
        key=lambda s: int(s.split("_")[1]),  # arr_0..arr_N in leaf order
    )
    assert keys, "child dumped no param leaves"
    # identical across processes (same global program)
    for k in keys:
        if exact_cross:
            np.testing.assert_array_equal(dumps[0][k], dumps[1][k], err_msg=k)
        else:
            np.testing.assert_allclose(
                dumps[0][k], dumps[1][k], rtol=1e-6, atol=1e-8, err_msg=k
            )
    assert abs(float(dumps[0]["loss"]) - float(dumps[1]["loss"])) < 1e-6

    # and equal to the single-process update on the full batch — pinned to
    # the children's CPU backend (a TPU-backend parent would compare
    # bf16-matmul params against f32 XLA:CPU params and fail spuriously)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from handyrl_tpu.parallel import make_mesh

    batch, module, params, args = build_ttt_batch()
    ref_params, ref_loss = run_one_train_step(
        module, args, make_mesh({"dp": 1}), params, batch
    )
    ref_leaves = [np.asarray(x) for x in jax.tree.leaves(ref_params)]
    assert len(ref_leaves) == len(keys)
    changed = False
    init_leaves = [np.asarray(x) for x in jax.tree.leaves(params)]
    for k, ref, init in zip(keys, ref_leaves, init_leaves):
        np.testing.assert_allclose(
            dumps[0][k], ref, rtol=2e-4, atol=2e-6, err_msg=k
        )
        changed = changed or not np.array_equal(ref, init)
    assert changed, "update was a no-op: params identical to init"
    assert abs(float(dumps[0]["loss"]) - ref_loss) < 1e-4 * max(1.0, abs(ref_loss))


@pytest.mark.slow
def test_two_process_train_step(tmp_path):
    """TrainContext.train_step under jax.distributed on the replicated-dp
    layout: identical params on both processes (bit-exact) and match vs
    the single-process update (SURVEY §2.5's gradient-plane claim)."""
    _two_process_train_and_compare(tmp_path, '{"dp": -1}', exact_cross=True)


@pytest.mark.slow
def test_two_process_train_step_tensor_parallel(tmp_path):
    """The same claim with a tensor-parallel axis spanning the global mesh:
    dp=2 x mp=2 over 2 processes — kernels sharded over 'mp', batch over
    'dp', GSPMD's cross-process collectives doing both the gradient
    all-reduce and the tp gathers.  Params are all-gathered before the
    dump (see run_one_train_step)."""
    _two_process_train_and_compare(tmp_path, '{"dp": 2, "mp": 2}', exact_cross=False)


# ---------------------------------------------------------------------------
# PR 12: the distributed EPOCH LOOP — the full Learner under jax.distributed
# ---------------------------------------------------------------------------

# A real 2-process x 2-virtual-device Learner run, end to end: role
# assignment, per-process local batch shards through put_batch, the
# coordinator-broadcast epoch cadence, coordinator-only checkpoints and
# metrics, the cross-host health plane idling cleanly, and an agreed
# shutdown after `epochs` epochs with bit-identical params everywhere.
_LEARNER_CHILD = r"""
import json, os, sys

port, hport, pid, nproc, outdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]), sys.argv[5]
)
extra = json.loads(sys.argv[6]) if len(sys.argv) > 6 else {}
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=%d" % int(extra.get("devices", 2))
)
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from handyrl_tpu.config import normalize_args
from handyrl_tpu.parallel import init_distributed

dist = {
    "coordinator_address": f"127.0.0.1:{port}",
    "num_processes": nproc,
    "process_id": pid,
    "initialization_timeout": 120.0,
    "heartbeat_interval": 1.0,
    "heartbeat_timeout": float(extra.get("heartbeat_timeout", 15.0)),
    "collective_timeout": 300.0,
    "health_port": hport,
}
dist.update(extra.get("dist") or {})
init_distributed(dist)

shared_dir = bool(extra.get("shared_dir"))
train = {
    "batch_size": 4,
    "forward_steps": 4,
    "minimum_episodes": 6,
    "update_episodes": 6,
    "maximum_episodes": 100,
    "epochs": int(extra.get("epochs", 2)),
    "num_batchers": 0,           # threaded pipeline: no child forks in CI
    "batch_pipeline": "thread",
    "eval_rate": 0.2,
    "mesh": {"dp": -1},          # 4 global devices, replicated params
    "worker": {"num_parallel": 2},
    "restart_epoch": int(extra.get("restart_epoch", 0)),
    "model_dir": os.path.join(outdir, "models" if shared_dir else f"models_{pid}"),
    "metrics_path": os.path.join(
        outdir, "metrics.jsonl" if shared_dir else f"metrics_{pid}.jsonl"
    ),
    "distributed": dist,
}
train.update(extra.get("train") or {})
args = normalize_args(
    {"env_args": {"env": extra.get("env", "TicTacToe")}, "train_args": train}
)

from handyrl_tpu.runtime.learner import Learner

learner = Learner(args)
code = learner.run()
leaves = [np.asarray(x) for x in jax.tree.leaves(learner.trainer.params_host())]
np.savez(os.path.join(outdir, f"final_{pid}{extra.get('tag', '')}.npz"), *leaves)
with open(os.path.join(outdir, f"done_{pid}{extra.get('tag', '')}.json"), "w") as f:
    json.dump(
        {"code": code, "model_epoch": learner.model_epoch,
         "steps": int(learner.trainer.steps)}, f
    )
# synchronized coordination-service disconnect (what train_main does): an
# unsynchronized atexit shutdown trips the service's own heartbeat
# timeout and SIGABRTs the slower rank
from handyrl_tpu.parallel.distributed import shutdown_distributed

shutdown_distributed()
sys.exit(code)
"""


def _spawn_learners(tmp_path, extra=None, env_extra=None, nproc=2, log_files=False):
    port, hport = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if env_extra:
        env.update(env_extra)
    blob = json.dumps(extra or {})
    if log_files:
        # unbounded-duration children (the host-loss e2es kill or outlive
        # them) must not block on a full stdout PIPE; unbuffered so the
        # poll loops see lines as they are printed
        env["PYTHONUNBUFFERED"] = "1"
    procs = []
    for pid in range(nproc):
        stdout = (
            open(os.path.join(str(tmp_path), f"learner_{pid}.log"), "wb")
            if log_files
            else subprocess.PIPE
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _LEARNER_CHILD, str(port), str(hport),
                 str(pid), str(nproc), str(tmp_path), blob],
                env=env,
                stdout=stdout,
                stderr=subprocess.STDOUT,
            )
        )
    return procs


def test_two_process_learner_epoch_loop(tmp_path):
    """Acceptance pin (non-slow, multihost CI step): a REAL 2-process
    Learner run completes 2 epochs under jax.distributed with params
    bit-identical on both processes, checkpoints/metrics written only by
    the coordinator, and a clean exit-0 shutdown on every rank.

    The run is TRACE-ENABLED (observability acceptance): each rank must
    write its own span file whose Perfetto export round-trips, and the
    coordinator's metrics.jsonl must carry rank_* aggregates covering
    BOTH ranks — the follower's snapshots arrive over the heartbeat
    relay, since PR 12 made metrics coordinator-only."""
    import numpy as np

    # generous heartbeat bound: this test pins the lockstep loop, not
    # detection latency, and a CI box under full-suite load can starve a
    # health thread for several seconds at a stretch
    procs = _spawn_learners(tmp_path, extra={
        "epochs": 2,
        "heartbeat_timeout": 45.0,
        "train": {"trace": {
            "enabled": True,
            "path": str(tmp_path / "trace.jsonl"),
            "flush_interval": 0.2,
        }},
    })
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    codes = [p.returncode for p in procs]
    assert codes == [0, 0], "".join(
        f"\n---- rank {i} rc={codes[i]} ----\n{out}" for i, out in enumerate(outs)
    )

    done = [json.load(open(tmp_path / f"done_{pid}.json")) for pid in range(2)]
    for d in done:
        assert d["code"] == 0
        assert d["model_epoch"] >= 2
        assert d["steps"] > 0
    # every process ran the SAME number of agreed steps
    assert done[0]["steps"] == done[1]["steps"]

    # bit-identical params on both processes (dp layout: exact)
    dumps = [np.load(tmp_path / f"final_{pid}.npz") for pid in range(2)]
    keys = sorted(dumps[0].files, key=lambda s: int(s.split("_")[1]))
    assert keys and dumps[1].files
    for k in keys:
        np.testing.assert_array_equal(dumps[0][k], dumps[1][k], err_msg=k)

    # exactly one writer: the coordinator owns checkpoints + metrics
    assert (tmp_path / "models_0" / "latest.ckpt").exists()
    assert (tmp_path / "models_0" / "MANIFEST.json").exists()
    assert (tmp_path / "metrics_0.jsonl").exists()
    assert not (tmp_path / "models_1").exists() or not any(
        (tmp_path / "models_1").iterdir()
    ), "non-coordinator wrote checkpoint files"
    assert not (tmp_path / "metrics_1.jsonl").exists(), "non-coordinator wrote metrics"
    records = [
        json.loads(l) for l in open(tmp_path / "metrics_0.jsonl") if l.strip()
    ]
    assert len(records) >= 2
    assert records[-1].get("dist_processes") == 2
    assert records[-1].get("dist_peer_loss_drains") == 0

    # every record carries the timestamp seam (the plot scripts' time axis)
    assert all("ts" in r and "t_mono" in r for r in records)

    # cross-host visibility (acceptance): some boundary record folds BOTH
    # ranks — the follower's per-epoch snapshot rode a heartbeat and the
    # coordinator aggregated it.  The first boundary may legitimately
    # precede the follower's first beat; a full run must not
    full = [r for r in records if r.get("rank_reports") == 2]
    assert full, [
        {k: v for k, v in r.items() if k.startswith("rank_")} for r in records
    ]
    last = full[-1]
    assert last["rank_missing_reports"] == 0
    assert last["rank_steps_min"] > 0
    assert last["rank_train_steps_per_sec_min"] > 0

    # trace-enabled run: one span file per rank (rank 1 derives its own
    # path), both parseable, and the merged Perfetto export round-trips
    from handyrl_tpu.utils.trace import read_trace

    trace0 = read_trace(str(tmp_path / "trace.jsonl"))
    trace1 = read_trace(str(tmp_path / "trace.rank1.jsonl"))
    names0 = {r["name"] for r in trace0}
    assert "train_step" in names0, sorted(names0)
    assert "cadence.agree_step" in names0, sorted(names0)
    assert "checkpoint.save" in names0, sorted(names0)
    assert {r["name"] for r in trace1} & {"cadence.agree_step", "train_step"}
    assert any(r["name"] == "health.heartbeat" for r in trace1)
    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    )
    sys.path.insert(0, scripts)
    try:
        from trace_export import export_chrome
    finally:
        sys.path.remove(scripts)
    out = export_chrome([trace0, trace1])
    xs = [e for e in out["traceEvents"] if e["ph"] == "X"]
    n_spans = sum(
        1 for recs in (trace0, trace1) for r in recs
        if r["name"] != "__trace_meta__"
    )
    assert len(xs) == n_spans and n_spans > 0
    assert {e["pid"] for e in xs} == {0, 1}  # both ranks on one timeline


# the resume-epoch broadcast (the non-coordinator auto-resume fix): the
# coordinator's manifest verdict must reach every process — rank 1 gets a
# DIFFERENT (empty) model_dir, so only the broadcast can tell it epoch 3
_RESUME_CHILD = r"""
import json, os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

from handyrl_tpu.parallel import broadcast_resume_epoch, init_distributed, is_coordinator
from handyrl_tpu.runtime.checkpoint import latest_verified_epoch

init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)
model_dir = os.path.join(outdir, "models_0" if is_coordinator() else f"models_{pid}")
local = latest_verified_epoch(model_dir) if is_coordinator() else 0
agreed = broadcast_resume_epoch(local)
with open(os.path.join(outdir, f"resume_{pid}.json"), "w") as f:
    json.dump({"local": local, "agreed": agreed}, f)
"""


def test_resume_epoch_broadcast_two_process(tmp_path):
    """Satellite pin: runtime/learner.py used to resolve
    latest_verified_epoch only on the coordinator, leaving other ranks at
    model_epoch 0.  The coordinator's verdict must be broadcast: rank 1's
    model_dir is EMPTY here, yet it must agree on the coordinator's
    verified epoch 3."""
    import numpy as np

    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    coord_dir = tmp_path / "models_0"
    params = {"w": np.arange(6, dtype=np.float32)}
    for epoch in (1, 3):
        save_epoch_snapshot(str(coord_dir), epoch, params, dict(params), epoch * 10)
    (tmp_path / "models_1").mkdir()

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RESUME_CHILD, str(port), str(pid), "2", str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = [p.communicate(timeout=240)[0].decode(errors="replace") for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"
    r0 = json.load(open(tmp_path / "resume_0.json"))
    r1 = json.load(open(tmp_path / "resume_1.json"))
    assert r0 == {"local": 3, "agreed": 3}
    assert r1 == {"local": 0, "agreed": 3}, "coordinator's verdict did not reach rank 1"


def test_init_distributed_timeout_is_loud(tmp_path):
    """Satellite pin: a dead/mis-addressed coordinator must fail startup
    within distributed.initialization_timeout with an error naming the
    coordinator address — never hang forever."""
    script = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
from handyrl_tpu.parallel import init_distributed
t0 = time.monotonic()
try:
    init_distributed({
        "coordinator_address": "127.0.0.1:1",  # nothing listens on port 1
        "num_processes": 2,
        "process_id": 1,
        "initialization_timeout": 5.0,
    })
except RuntimeError as exc:
    msg = str(exc)
    assert "127.0.0.1:1" in msg, msg
    assert "initialization_timeout" in msg, msg
    print("LOUD-TIMEOUT-OK %.1fs" % (time.monotonic() - t0))
    sys.exit(0)
print("no error raised")
sys.exit(1)
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, timeout=180
    )
    text = out.stdout.decode(errors="replace") + out.stderr.decode(errors="replace")
    assert out.returncode == 0, text
    assert "LOUD-TIMEOUT-OK" in text


@pytest.mark.slow
def test_sentinel_rollback_is_bit_coherent_across_processes(tmp_path):
    """Tentpole (c) pin: a sentinel rollback under jax.distributed must
    leave every process on the SAME verified snapshot.  Rank 1 runs with
    its own EMPTY model_dir — before the rollback agreement + params
    broadcast it would scan that empty dir, keep its diverged params, and
    silently break the bit-identical invariant while the coordinator
    rolled back."""
    import numpy as np

    procs = _spawn_learners(
        tmp_path,
        extra={
            "epochs": 4,
            "heartbeat_timeout": 45.0,  # pinning rollback coherence, not bounds
            "train": {"sentinel_rollback_after": 2},
        },
        # lr poisoned with NaN from SGD step 10 ONWARD on every rank (the
        # step counter is cadence-agreed, so the streak is identical; a
        # bounded window could be reset by a clean tail step before the
        # epoch-end threshold check — the test_sentinel e2e pattern)
        env_extra={"HANDYRL_FAULT_NAN_AT_STEP": "10:1000000"},
    )
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child rc={p.returncode}:\n{out}"

    records = [
        json.loads(l) for l in open(tmp_path / "metrics_0.jsonl") if l.strip()
    ]
    last = records[-1]
    assert last.get("sentinel_skipped_steps", 0) >= 2, outs[0]
    assert last.get("sentinel_rollbacks", 0) >= 1, outs[0]
    assert "rolled back to verified epoch" in outs[0]

    dumps = [np.load(tmp_path / f"final_{pid}.npz") for pid in range(2)]
    keys = sorted(dumps[0].files, key=lambda s: int(s.split("_")[1]))
    assert keys
    for k in keys:
        np.testing.assert_array_equal(dumps[0][k], dumps[1][k], err_msg=k)


def test_init_distributed_retry_is_real(monkeypatch):
    """The backoff-retry around jax.distributed.initialize must reset the
    half-initialized global state between attempts: jax assigns
    global_state.client BEFORE connect(), so without the reset every
    retry dies instantly on 'should only be called once' and the loop
    absorbs nothing."""
    import jax
    from jax._src.distributed import global_state

    from handyrl_tpu.parallel import distributed as D

    # the reset helper clears a poisoned state even when the client
    # object refuses a clean shutdown
    class _Stuck:
        def shutdown(self):
            raise RuntimeError("never connected")

    monkeypatch.setattr(global_state, "client", _Stuck(), raising=False)
    D._reset_half_initialized_state()
    assert global_state.client is None

    # ...and the init loop really reaches a second attempt
    attempts = []

    def fake_initialize(**kwargs):
        attempts.append(kwargs)
        if len(attempts) == 1:
            raise RuntimeError("UNAVAILABLE: connect failed")

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    rank = D.init_distributed(
        {
            "coordinator_address": "127.0.0.1:12345",
            "num_processes": 2,
            "process_id": 0,  # rank 0: no TCP pre-flight
            "initialization_timeout": 30.0,
        }
    )
    assert rank == 0
    assert len(attempts) == 2


def test_await_proceed_returns_delivered_verdict_after_stop():
    """The learner's shutdown path is proceed(stop) immediately followed
    by trainer.stop(); when stop_event wins that race the delivered
    verdict must STILL surface so the final agree_stop broadcast is
    dispatched — swallowing it abandons every follower inside the
    collective until the watchdog exits them 75 out of a clean run
    (reproduced under load before the fix)."""
    import queue as queue_mod
    import threading
    from types import SimpleNamespace

    from handyrl_tpu.runtime.trainer import Trainer

    t = SimpleNamespace(
        stop_event=threading.Event(), _proceed_queue=queue_mod.Queue(maxsize=1)
    )
    t._proceed_queue.put(True)
    t.stop_event.set()  # stop() already landed
    assert Trainer._await_proceed(t) is True

    t2 = SimpleNamespace(
        stop_event=threading.Event(), _proceed_queue=queue_mod.Queue(maxsize=1)
    )
    t2.stop_event.set()
    assert Trainer._await_proceed(t2) is None  # no verdict: no broadcast


def test_shutdown_coherent_gates_the_distributed_shutdown_barrier():
    """train_main only joins the synchronized jax.distributed.shutdown
    barrier when every rank will reach it: a clean finish or a cadence-
    AGREED drain.  After a follower-LOCAL drain the peers never join the
    barrier (they are still training, or leaving via os._exit), so waiting
    in it ends in the coordination service's SIGABRT instead of the
    promised exit 75 (docs/fault_tolerance.md, one-rank SIGTERM row)."""
    from types import SimpleNamespace

    from handyrl_tpu.runtime.learner import Learner

    coherent = Learner.shutdown_coherent.fget

    def state(nprocs, drain_requested, drain_agreed):
        return SimpleNamespace(
            _dist_nprocs=nprocs,
            _drain_requested=drain_requested,
            trainer=SimpleNamespace(drain_agreed=drain_agreed),
        )

    assert coherent(state(1, True, False))   # single-process: shutdown no-ops
    assert coherent(state(2, False, False))  # clean agreed finish
    assert coherent(state(2, True, True))    # coordinator drain, agreed by all
    assert not coherent(state(2, True, False))  # follower-local drain


# ---------------------------------------------------------------------------
# PR 12: host-loss e2es — the cross-host health plane under real process death
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_host_loss_kill_rank1_drain_exit75_and_resume(tmp_path):
    """Acceptance pin (slow leg): HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH
    kills rank 1 at its first published epoch.  The surviving coordinator
    must detect the loss within the heartbeat bound (no indefinite
    collective hang), drain-save a manifest-verified checkpoint, and exit
    75; a relaunch of both ranks with restart_epoch: -1 then auto-resumes
    every process from that checkpoint and finishes cleanly."""
    from handyrl_tpu.runtime.checkpoint import latest_verified_epoch

    procs = _spawn_learners(
        tmp_path,
        extra={"epochs": 8, "shared_dir": True, "heartbeat_timeout": 6.0},
        env_extra={"HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH": "1:1"},
    )
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    # rank 1 died hard by injection
    assert procs[1].returncode == 1, f"rank1 rc={procs[1].returncode}:\n{outs[1]}"
    assert "HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH" in outs[1]
    # the survivor detected the loss, drain-saved, exited EX_TEMPFAIL
    assert procs[0].returncode == 75, f"rank0 rc={procs[0].returncode}:\n{outs[0]}"
    assert "host fault" in outs[0] and "peer process 1 lost" in outs[0], outs[0]
    assert "drain checkpoint" in outs[0], outs[0]
    drained = latest_verified_epoch(str(tmp_path / "models"))
    assert drained >= 1, "no verified drain checkpoint on disk"
    # the final pre-exit metrics record carries the dist_* event counters
    records = [
        json.loads(l) for l in open(tmp_path / "metrics.jsonl") if l.strip()
    ]
    assert records[-1].get("dist_peer_loss_drains", 0) >= 1

    # relaunch both ranks: every process must resume the SAME verified
    # epoch (coordinator scan + broadcast) and run to a clean finish
    procs = _spawn_learners(
        tmp_path,
        extra={"epochs": drained + 1, "shared_dir": True,
               "restart_epoch": -1, "tag": "_resumed"},
    )
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"relaunch rc={p.returncode}:\n{out}"
        assert f"auto-resume (restart_epoch: -1): epoch {drained}" in out, out
    done = [json.load(open(tmp_path / f"done_{pid}_resumed.json")) for pid in range(2)]
    for d in done:
        assert d["model_epoch"] >= drained + 1


@pytest.mark.slow
def test_coordinator_death_survivor_exits_loudly(tmp_path):
    """Acceptance pin (slow leg): when the COORDINATOR dies, the follower
    must exit loudly within the bound — never hang in the next collective.

    Two loud paths exist, and which one wins is a race the follower must
    survive either way: jax's own coordination-service client usually sees
    the leader's gRPC socket close within milliseconds and terminates the
    process with a fatal abort naming the leader death; the health plane's
    heartbeat bound (exit 75, ``host fault (coordinator_loss)``) covers
    the case the service cannot see — a coordinator host that wedges or
    partitions while its sockets stay up (pinned socket-free in
    tests/test_health.py, where the client clock drives the timeout).
    Either way: nonzero within the bound, a line naming the coordinator,
    no hang — which is the acceptance claim."""
    procs = _spawn_learners(
        tmp_path,
        extra={"epochs": 8, "shared_dir": True, "heartbeat_timeout": 6.0},
        env_extra={"HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH": "1:0"},
    )
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    assert procs[0].returncode == 1, f"rank0 rc={procs[0].returncode}:\n{outs[0]}"
    assert "HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH" in outs[0]
    rc1 = procs[1].returncode
    assert rc1 != 0 and rc1 is not None, f"follower exited 0:\n{outs[1]}"
    loud_health = "host fault" in outs[1] and "coordinator" in outs[1]
    loud_service = (
        "Terminating process because the JAX distributed service" in outs[1]
        or "coordination service" in outs[1]
    )
    assert loud_health or loud_service, (
        f"follower exit (rc={rc1}) was not loud about the coordinator:\n{outs[1]}"
    )


# ---------------------------------------------------------------------------
# PR 19: pod-slice — device planes + the actor-host tier under jax.distributed
# ---------------------------------------------------------------------------


def _assert_bit_identical_finals(tmp_path, nproc=2, tag=""):
    import numpy as np

    done = [
        json.load(open(tmp_path / f"done_{pid}{tag}.json")) for pid in range(nproc)
    ]
    for d in done:
        assert d["code"] == 0
        assert d["steps"] > 0
    assert len({d["steps"] for d in done}) == 1, done
    dumps = [np.load(tmp_path / f"final_{pid}{tag}.npz") for pid in range(nproc)]
    keys = sorted(dumps[0].files, key=lambda s: int(s.split("_")[1]))
    assert keys
    for k in keys:
        for d in dumps[1:]:
            np.testing.assert_array_equal(dumps[0][k], d[k], err_msg=k)


@pytest.mark.slow
def test_two_process_device_batch_pipeline_parity(tmp_path):
    """Tentpole acceptance pin (rung 1): `batch_pipeline: device` under a
    REAL 2-process run.  Each process stages its own host-born episodes
    into process-LOCAL device rings, samples its shard of the global batch
    on its own devices, and the shards meet the collective train step
    through the make_array_from_process_local_data seam — params must stay
    bit-identical on both ranks after 2 epochs, and the metrics must show
    the DEVICE pipeline actually ran (a silent fall-back to threads would
    pass the parity check while testing nothing)."""
    procs = _spawn_learners(tmp_path, extra={
        "epochs": 2,
        "heartbeat_timeout": 45.0,
        "train": {
            "batch_pipeline": "device",
            # TicTacToe turn mode on the device stage needs the observation
            # flag (windows carry all-player observation rows)
            "observation": True,
            "device_stage_lanes": 4,
            "device_stage_chunk": 8,
            "device_stage_slots": 64,
            "eval_rate": 0.0,
        },
    })
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    codes = [p.returncode for p in procs]
    assert codes == [0, 0], "".join(
        f"\n---- rank {i} rc={codes[i]} ----\n{out}" for i, out in enumerate(outs)
    )
    _assert_bit_identical_finals(tmp_path)
    records = [
        json.loads(l) for l in open(tmp_path / "metrics_0.jsonl") if l.strip()
    ]
    assert any(r.get("pipeline") == "device" for r in records), (
        [r.get("pipeline") for r in records], outs[0]
    )


@pytest.mark.slow
def test_two_process_split_plane_device_pipeline_e2e(tmp_path):
    """Tentpole acceptance pin (rung 1, the pod-slice shape itself): a
    REAL 2-process run where each rank's 4 virtual devices are carved
    2 + 2 — the leading pair joins the GLOBAL learner mesh (collective
    train step across hosts), the trailing pair is that rank's process-
    local actor plane running the streaming device rollout into its own
    DeviceReplay rings.  Per-rank RNGs are decorrelated (seed +
    1009*rank), so the ranks ingest DIFFERENT episodes and sample
    DIFFERENT local shards, yet the collective step must keep params
    bit-identical on both processes; the coordinator's metrics must carry
    the plane-health keys with both planes having actually worked."""
    procs = _spawn_learners(tmp_path, extra={
        "devices": 4,
        "env": "ParallelTicTacToe",
        "epochs": 2,
        "heartbeat_timeout": 45.0,
        "train": {
            "plane": "split",
            "actor_chips": 2,
            "param_refresh_updates": 2,
            # two ranks compiling rollout + ingest + the collective step
            # concurrently on shared host cores can silence the rollout
            # thread for minutes; the default 120s bound would degrade a
            # HEALTHY run split -> fused mid-test (seen in CI soak)
            "plane_stall_timeout": 600.0,
            "mesh": {"dp": -1},
            "turn_based_training": False,
            "observation": False,
            "batch_size": 8,
            "forward_steps": 4,
            "burn_in_steps": 0,
            "device_rollout_games": 8,
            "device_replay": True,
            "device_replay_slots": 64,
            "device_replay_k_steps": 16,
            "minimum_episodes": 20,
            "update_episodes": 30,
            "maximum_episodes": 400,
            "eval_rate": 0.0,
            "worker": {"num_parallel": 1},
        },
    })
    outs = [p.communicate(timeout=420)[0].decode(errors="replace") for p in procs]
    codes = [p.returncode for p in procs]
    assert codes == [0, 0], "".join(
        f"\n---- rank {i} rc={codes[i]} ----\n{out}" for i, out in enumerate(outs)
    )
    _assert_bit_identical_finals(tmp_path)
    records = [
        json.loads(l) for l in open(tmp_path / "metrics_0.jsonl") if l.strip()
    ]
    assert records[-1].get("dist_processes") == 2
    epoch_rows = [r for r in records if "plane_actor_busy_frac" in r]
    assert epoch_rows, f"no plane_* keys in metrics_0.jsonl: {records}"
    assert max(r["plane_actor_busy_frac"] for r in epoch_rows) > 0
    assert max(r["plane_xfer_bytes_per_sec"] for r in epoch_rows) > 0


# rung 2: a dedicated actor host — runs ONLY the data plane (streaming
# device rollout), ships records to the learner's plane gateway over TCP,
# polls versioned params back.  Deliberately outside jax.distributed.
_ACTOR_CHILD = r"""
import json, os, sys

outdir = sys.argv[1]
extra = json.loads(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=%d" % int(extra.get("devices", 2))
)
import jax

jax.config.update("jax_platforms", "cpu")

from handyrl_tpu.config import normalize_args
from handyrl_tpu.runtime.actor_host import actor_host_main

args = normalize_args(
    {"env_args": {"env": extra.get("env", "ParallelTicTacToe")},
     "train_args": extra["train"]}
)
actor_host_main(args)
"""


def _pod_slice_train(plane_port):
    # one learner process (2 virtual devices, fused plane, device replay)
    # + one actor host shipping over the gateway; the learner's OWN
    # streaming rollout keeps generating too, so losing the actor host
    # degrades throughput without stalling the cadence
    return {
        "turn_based_training": False,
        "observation": False,
        "batch_size": 8,
        "forward_steps": 4,
        "burn_in_steps": 0,
        "plane_stall_timeout": 600.0,  # compile storms are not stalls
        "device_rollout_games": 8,
        "device_replay": True,
        "device_replay_slots": 64,
        "device_replay_k_steps": 16,
        "minimum_episodes": 20,
        "update_episodes": 30,
        "maximum_episodes": 4000,
        "eval_rate": 0.0,
        "worker": {"num_parallel": 1},
        "mesh": {"dp": -1},
        # NO "distributed" key: the learner child's dist dict (which
        # carries actor_hosts + plane_port via extra["dist"]) must survive
        # the train.update() merge; _spawn_actor overrides it wholesale
    }


def _spawn_actor(tmp_path, plane_port, log_path, extra=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"  # the tests poll the log for lines
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    train = _pod_slice_train(plane_port)
    train["distributed"] = {
        # host part is what the actor dials; the port is the explicit
        # plane_port, so the coordinator port here is never used
        "coordinator_address": "127.0.0.1:6000",
        "num_processes": 1,
        "process_id": 0,
        "role": "actor",
        "plane_port": plane_port,
        "initialization_timeout": 180.0,
    }
    blob = json.dumps(dict(extra or {}, train=train))
    return subprocess.Popen(
        [sys.executable, "-c", _ACTOR_CHILD, str(tmp_path), blob],
        env=env,
        stdout=open(log_path, "wb"),
        stderr=subprocess.STDOUT,
    )


def _await_actor_connected(actor, log_path, learners, deadline_s=240):
    import time

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        text = log_path.read_bytes() if log_path.exists() else b""
        if b"connected to plane gateway" in text:
            return
        assert actor.poll() is None, (
            f"actor host died before connecting (rc={actor.returncode}):\n"
            + text.decode(errors="replace")
        )
        for p in learners:
            assert p.poll() is None, (
                f"learner exited (rc={p.returncode}) before the actor host "
                "connected"
            )
        time.sleep(0.5)
    raise AssertionError(
        "actor host never connected:\n"
        + (log_path.read_bytes().decode(errors="replace") if log_path.exists() else "")
    )


@pytest.mark.slow
def test_actor_host_loss_is_degradable(tmp_path):
    """Fault-matrix pin (rung 2, the degradable direction): killing a
    connected actor host must NOT gate the learner — the gateway logs the
    disconnect, bumps dist_actor_host_losses, and the learner's own
    rollout absorbs the game quota to a clean exit-0 finish."""
    plane_port = _free_port()
    learners = _spawn_learners(
        tmp_path,
        nproc=1,
        log_files=True,  # no PIPE: nobody reads while we await the actor
        extra={
            "env": "ParallelTicTacToe",
            "epochs": 3,
            "heartbeat_timeout": 45.0,
            "dist": {"actor_hosts": 1, "plane_port": plane_port},
            "train": _pod_slice_train(plane_port),
        },
    )
    actor_log = tmp_path / "actor.log"
    actor = _spawn_actor(tmp_path, plane_port, actor_log)
    try:
        _await_actor_connected(actor, actor_log, learners)
    finally:
        actor.kill()
    actor.wait(timeout=60)
    try:
        learners[0].wait(timeout=420)
    finally:
        if learners[0].poll() is None:
            learners[0].kill()
    out = (tmp_path / "learner_0.log").read_bytes().decode(errors="replace")
    assert learners[0].returncode == 0, out
    records = [
        json.loads(l) for l in open(tmp_path / "metrics_0.jsonl") if l.strip()
    ]
    tiered = [r for r in records if "dist_actor_host_losses" in r]
    assert tiered, f"no actor-tier keys in metrics: {records}"
    assert tiered[-1]["dist_actor_host_losses"] >= 1, (tiered, out)
    # before the kill the host was COUNTED live at least once, or records
    # actually landed (either proves the tier was attached, not idle)
    assert (
        max(r["dist_actor_hosts"] for r in tiered) >= 1
        or "plane: records" in out
        or any(r.get("plane_xfer_bytes_per_sec", 0) > 0 for r in records)
    ), (tiered, out)


@pytest.mark.slow
def test_learner_loss_actor_exits_75(tmp_path):
    """Fault-matrix pin (rung 2, the loud direction): when the learner
    tier dies, a dedicated actor host must NOT spin generating against
    unowned params — its next gateway call raises, it announces the fault
    and exits 75 (EX_TEMPFAIL) for the supervisor to relaunch."""
    plane_port = _free_port()
    learners = _spawn_learners(
        tmp_path,
        nproc=1,
        log_files=True,  # killed mid-run: must not block on a full PIPE
        extra={
            "env": "ParallelTicTacToe",
            "epochs": 1000,
            "heartbeat_timeout": 45.0,
            "dist": {"actor_hosts": 1, "plane_port": plane_port},
            "train": dict(_pod_slice_train(plane_port), maximum_episodes=10 ** 7),
        },
    )
    actor_log = tmp_path / "actor.log"
    actor = _spawn_actor(tmp_path, plane_port, actor_log)
    try:
        _await_actor_connected(actor, actor_log, learners)
        learners[0].kill()
        learners[0].wait(timeout=60)
        rc = actor.wait(timeout=420)
    finally:
        for p in learners + [actor]:
            if p.poll() is None:
                p.kill()
    out = actor_log.read_bytes().decode(errors="replace")
    assert rc == 75, f"actor rc={rc}:\n{out}"
    assert "plane gateway lost" in out, out
    assert "host fault (learner_loss)" in out, out
