"""Multi-host learner plane: jax.distributed over two localhost processes.

The reference has no multi-host learner at all (nn.DataParallel is
single-process, reference train.py:340-341); SURVEY.md §2.5 prescribes
jax.distributed + XLA collectives for the gradient plane.  This test runs
TWO real OS processes, each with 2 virtual CPU devices, connected through
``init_distributed`` — the global mesh spans 4 devices — and checks:

* a dp-sharded global array assembled from per-process local shards
  (``TrainContext.put_batch``'s multi-process path) reduces correctly
  through a jitted collective;
* only the coordinator (process 0) passes the checkpoint/metrics guard.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import json, os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from handyrl_tpu.parallel import (
    init_distributed,
    is_coordinator,
    local_batch_size,
    make_mesh,
)

rank = init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)
assert rank == pid, (rank, pid)
assert jax.process_count() == nproc
assert len(jax.devices()) == 2 * nproc  # global device view

mesh = make_mesh({"dp": -1})
sharding = NamedSharding(mesh, PartitionSpec("dp"))

# per-process local shard of a global batch: process p contributes rows p+1
B_local = local_batch_size(4)
local = np.full((B_local, 3), pid + 1.0, np.float32)
arr = jax.make_array_from_process_local_data(sharding, local)
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)

# put_batches' multi-process branch (fused_steps path): stack k local
# batch shards -> (k, B, ...) global tree, reduce through a collective
from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.parallel import TrainContext

cfg = normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": {"batch_size": 4}})
targs = dict(cfg["train_args"]); targs["env"] = cfg["env_args"]
ctx = TrainContext(make_env(cfg["env_args"]).net(), targs, mesh)
host_batches = [
    {"action": np.full((B_local, 1), pid + 1.0, np.float32)} for _ in range(3)
]
stacked = ctx.put_batches(host_batches)
ssum = jax.jit(
    lambda t: t["action"].sum(), out_shardings=NamedSharding(mesh, PartitionSpec())
)(stacked)
# 3 stacked batches x (2 local rows x 1 col) x (1 + 2) across processes
assert abs(float(ssum) - 18.0) < 1e-6, float(ssum)

# the checkpoint/metrics guard: exactly one writer
if is_coordinator():
    with open(os.path.join(outdir, "result.json"), "w") as f:
        json.dump({"total": float(total), "process_count": jax.process_count()}, f)
else:
    with open(os.path.join(outdir, f"noncoord_{pid}.txt"), "w") as f:
        f.write("guarded")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cpu_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(port), str(pid), "2", str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    result = json.load(open(tmp_path / "result.json"))
    assert result["process_count"] == 2
    # global sum: 2 local rows x 3 cols of (pid+1) per process = 6*1 + 6*2
    assert abs(result["total"] - 18.0) < 1e-6
    assert (tmp_path / "noncoord_1.txt").exists()
    assert not (tmp_path / "noncoord_0.txt").exists()
