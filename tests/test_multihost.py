"""Multi-host learner plane: jax.distributed over two localhost processes.

The reference has no multi-host learner at all (nn.DataParallel is
single-process, reference train.py:340-341); SURVEY.md §2.5 prescribes
jax.distributed + XLA collectives for the gradient plane.  This test runs
TWO real OS processes, each with 2 virtual CPU devices, connected through
``init_distributed`` — the global mesh spans 4 devices — and checks:

* a dp-sharded global array assembled from per-process local shards
  (``TrainContext.put_batch``'s multi-process path) reduces correctly
  through a jitted collective;
* only the coordinator (process 0) passes the checkpoint/metrics guard.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import json, os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from handyrl_tpu.parallel import (
    init_distributed,
    is_coordinator,
    local_batch_size,
    make_mesh,
)

rank = init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)
assert rank == pid, (rank, pid)
assert jax.process_count() == nproc
assert len(jax.devices()) == 2 * nproc  # global device view

mesh = make_mesh({"dp": -1})
sharding = NamedSharding(mesh, PartitionSpec("dp"))

# per-process local shard of a global batch: process p contributes rows p+1
B_local = local_batch_size(4)
local = np.full((B_local, 3), pid + 1.0, np.float32)
arr = jax.make_array_from_process_local_data(sharding, local)
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)

# put_batches' multi-process branch (fused_steps path): stack k local
# batch shards -> (k, B, ...) global tree, reduce through a collective
from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.parallel import TrainContext

cfg = normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": {"batch_size": 4}})
targs = dict(cfg["train_args"]); targs["env"] = cfg["env_args"]
ctx = TrainContext(make_env(cfg["env_args"]).net(), targs, mesh)
host_batches = [
    {"action": np.full((B_local, 1), pid + 1.0, np.float32)} for _ in range(3)
]
stacked = ctx.put_batches(host_batches)
ssum = jax.jit(
    lambda t: t["action"].sum(), out_shardings=NamedSharding(mesh, PartitionSpec())
)(stacked)
# 3 stacked batches x (2 local rows x 1 col) x (1 + 2) across processes
assert abs(float(ssum) - 18.0) < 1e-6, float(ssum)

# the checkpoint/metrics guard: exactly one writer
if is_coordinator():
    with open(os.path.join(outdir, "result.json"), "w") as f:
        json.dump({"total": float(total), "process_count": jax.process_count()}, f)
else:
    with open(os.path.join(outdir, f"noncoord_{pid}.txt"), "w") as f:
        f.write("guarded")
"""


# The gradient plane's core claim (SURVEY §2.5): TrainContext.train_step
# — value_and_grad + the GSPMD gradient all-reduce — executed ACROSS
# processes on per-process local batch shards must produce the same
# params on every process, and the same update a single process computes
# from the full batch.  Both processes seed identically, generate the
# SAME episodes/windows via the real generator, then feed only their own
# rows through put_batch's make_array_from_process_local_data branch.
_TRAIN_CHILD = r"""
import json, os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
mesh_spec = json.loads(sys.argv[5]) if len(sys.argv) > 5 else {"dp": -1}
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from handyrl_tpu.parallel import init_distributed, is_coordinator, make_mesh

init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)

sys.path.insert(0, os.getcwd())  # parent sets cwd to the tests dir
from test_multihost import build_ttt_batch, run_one_train_step

batch, module, params, args = build_ttt_batch()
mesh = make_mesh(mesh_spec)
B_local = batch["action"].shape[0] // nproc
local = jax.tree.map(lambda x: x[pid * B_local:(pid + 1) * B_local], batch)
new_params, loss = run_one_train_step(module, args, mesh, params, local)

leaves = [np.asarray(x) for x in jax.tree.leaves(new_params)]
np.savez(os.path.join(outdir, f"params_{pid}.npz"), loss=loss, *leaves)
"""


# Sequence-parallel plane across processes: masked ring attention with T
# sharded over an 'sp' axis spanning the 2-process global mesh — the K/V
# ring's ppermute hops cross process boundaries.  Inputs are seeded
# identically everywhere; each process contributes its local T rows via
# make_array_from_process_local_data, and the sharded output is
# all-gathered and dumped for comparison against the single-process
# einsum reference.
_RING_CHILD = r"""
import os, sys

port, pid, nproc, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from handyrl_tpu.ops import masked_ring_self_attention
from handyrl_tpu.parallel import init_distributed, make_mesh

init_distributed(
    {"coordinator_address": f"127.0.0.1:{port}", "num_processes": nproc, "process_id": pid}
)

sys.path.insert(0, os.getcwd())
from test_multihost import build_ring_inputs

q, k, v, key_mask, slopes, window = build_ring_inputs()
mesh = make_mesh({"sp": -1})
T = q.shape[1]
T_proc = T // nproc

def put(x, spec):
    sh = NamedSharding(mesh, spec)
    local = x[:, pid * T_proc:(pid + 1) * T_proc]
    return jax.make_array_from_process_local_data(sh, np.asarray(local))

qg = put(q, P(None, "sp", None, None))
kg = put(k, P(None, "sp", None, None))
vg = put(v, P(None, "sp", None, None))
mg = put(key_mask, P(None, "sp"))

out = masked_ring_self_attention(qg, kg, vg, mg, jax.numpy.asarray(slopes), mesh, window=window)
rep = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))(out)
np.savez(os.path.join(outdir, f"ring_{pid}.npz"), out=np.asarray(jax.device_get(rep)))
"""


def build_ring_inputs():
    """Deterministic (q, k, v, key_mask, slopes, window) for the ring test —
    same values in every process (fixed PRNG keys, host numpy)."""
    import numpy as np

    rng = np.random.RandomState(99)
    B, T, H, D = 2, 32, 2, 8
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)
    key_mask = (rng.rand(B, T) < 0.7).astype(np.float32)
    slopes = (2.0 ** -np.arange(1, H + 1)).astype(np.float32)
    return q, k, v, key_mask, slopes, 8


@pytest.mark.slow
def test_two_process_ring_attention(tmp_path):
    """Masked ring attention with the 'sp' axis spanning 2 processes must
    match the single-process einsum reference — the sequence-parallel
    plane's cross-host claim (its ppermute ring hops process boundaries)."""
    import numpy as np

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RING_CHILD, str(port), str(pid), "2", str(tmp_path)],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = [p.communicate(timeout=300)[0].decode(errors="replace") for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    import jax

    jax.config.update("jax_platforms", "cpu")
    from handyrl_tpu.ops.flash_attention import masked_attention_reference

    q, k, v, key_mask, slopes, window = build_ring_inputs()
    ref = np.asarray(
        masked_attention_reference(q, k, v, key_mask, slopes, window=window)
    )
    for pid in range(2):
        got = np.load(tmp_path / f"ring_{pid}.npz")["out"]
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)


def build_ttt_batch():
    """Deterministic TicTacToe batch + module + init params (seeded global
    RNGs: every caller that seeds the same way gets byte-identical data)."""
    import random as pyrandom

    import numpy as np

    pyrandom.seed(1234)
    np.random.seed(1234)

    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, RandomModel, init_variables
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    cfg = normalize_args(
        # compaction off: the multi-process path skips it by design (all
        # processes must agree on global shapes), so the single-process
        # reference run must train the same uncompacted program
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"batch_size": 4, "compact_padding": False}}
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 8:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(
            args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
        )
        if w is not None:
            windows.append(w)
    return make_batch(windows, args), module, variables["params"], args


def run_one_train_step(module, args, mesh, params, local_batch):
    """One real TrainContext.train_step; returns (host params, loss).

    Params are re-laid-out replicated before the host fetch: under an
    'mp' mesh axis the updated kernels are SHARDED across the global
    devices, and in a multi-process run device_get of a partially
    non-addressable array fails — the jitted identity performs the
    all-gather (a no-op when already replicated)."""
    import jax
    import numpy as np

    from handyrl_tpu.parallel import TrainContext

    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(params)
    device_batch = ctx.put_batch(local_batch)
    state, metrics = ctx.train_step(state, device_batch, 1e-3)
    gathered = jax.jit(lambda t: t, out_shardings=ctx._replicated)(state["params"])
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), gathered)
    return host, float(jax.device_get(metrics["total"]))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cpu_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(port), str(pid), "2", str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    result = json.load(open(tmp_path / "result.json"))
    assert result["process_count"] == 2
    # global sum: 2 local rows x 3 cols of (pid+1) per process = 6*1 + 6*2
    assert abs(result["total"] - 18.0) < 1e-6
    assert (tmp_path / "noncoord_1.txt").exists()
    assert not (tmp_path / "noncoord_0.txt").exists()


def _two_process_train_and_compare(tmp_path, mesh_spec: str, exact_cross: bool):
    """Spawn 2 jax.distributed processes x 2 virtual devices running the
    REAL jitted sharded update on local batch shards under ``mesh_spec``,
    then assert (a) both processes end with the same params and (b) those
    params match a single-process update on the full batch (same math up
    to float reassociation — the sharded program's reduction order may
    differ, so the cross-process check is exact only for the replicated
    dp layout)."""
    import json

    import numpy as np

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _TRAIN_CHILD, str(port), str(pid), "2",
             str(tmp_path), mesh_spec],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}"

    dumps = [np.load(tmp_path / f"params_{pid}.npz") for pid in range(2)]
    keys = sorted(
        (k for k in dumps[0].files if k != "loss"),
        key=lambda s: int(s.split("_")[1]),  # arr_0..arr_N in leaf order
    )
    assert keys, "child dumped no param leaves"
    # identical across processes (same global program)
    for k in keys:
        if exact_cross:
            np.testing.assert_array_equal(dumps[0][k], dumps[1][k], err_msg=k)
        else:
            np.testing.assert_allclose(
                dumps[0][k], dumps[1][k], rtol=1e-6, atol=1e-8, err_msg=k
            )
    assert abs(float(dumps[0]["loss"]) - float(dumps[1]["loss"])) < 1e-6

    # and equal to the single-process update on the full batch — pinned to
    # the children's CPU backend (a TPU-backend parent would compare
    # bf16-matmul params against f32 XLA:CPU params and fail spuriously)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from handyrl_tpu.parallel import make_mesh

    batch, module, params, args = build_ttt_batch()
    ref_params, ref_loss = run_one_train_step(
        module, args, make_mesh({"dp": 1}), params, batch
    )
    ref_leaves = [np.asarray(x) for x in jax.tree.leaves(ref_params)]
    assert len(ref_leaves) == len(keys)
    changed = False
    init_leaves = [np.asarray(x) for x in jax.tree.leaves(params)]
    for k, ref, init in zip(keys, ref_leaves, init_leaves):
        np.testing.assert_allclose(
            dumps[0][k], ref, rtol=2e-4, atol=2e-6, err_msg=k
        )
        changed = changed or not np.array_equal(ref, init)
    assert changed, "update was a no-op: params identical to init"
    assert abs(float(dumps[0]["loss"]) - ref_loss) < 1e-4 * max(1.0, abs(ref_loss))


@pytest.mark.slow
def test_two_process_train_step(tmp_path):
    """TrainContext.train_step under jax.distributed on the replicated-dp
    layout: identical params on both processes (bit-exact) and match vs
    the single-process update (SURVEY §2.5's gradient-plane claim)."""
    _two_process_train_and_compare(tmp_path, '{"dp": -1}', exact_cross=True)


@pytest.mark.slow
def test_two_process_train_step_tensor_parallel(tmp_path):
    """The same claim with a tensor-parallel axis spanning the global mesh:
    dp=2 x mp=2 over 2 processes — kernels sharded over 'mp', batch over
    'dp', GSPMD's cross-process collectives doing both the gradient
    all-reduce and the tp gathers.  Params are all-gathered before the
    dump (see run_one_train_step)."""
    _two_process_train_and_compare(tmp_path, '{"dp": 2, "mp": 2}', exact_cross=False)
