"""Data-flywheel tests (marker: flywheel) — the quality-guarded
production loop (docs/serving.md §Data flywheel).

Three layers of acceptance:

* **Parity** — a served session assembled by the HarvestRecorder must be
  bit-identical (same zlib block bytes, through the wire codec) to the
  episode the self-play Generator builds for the SAME trajectory, and
  ring ingest of harvested blobs must match ``make_batch`` key by key
  (the ISSUE 6 parity style).  Both paths finalize through the one
  shared ``finalize_episode`` recipe, so any difference is an assembly
  bug, not sampling noise.

* **Guards** — staleness-drop / malformed-session-drop units on both
  sides of the wire (server HarvestRecorder, learner HarvestIngestor),
  the promotion gate + quality sentinel on a stub router, and the
  shared transient-fault retry discipline (actor-host reconnect shape,
  fleet stats-poll hardening) — all socket-free.

* **Flagship e2e** (slow) — a ``--serve`` + ``--train`` pair improves
  measured win rate against scripted clients using ONLY served-traffic
  episodes (zero self-play workers, ``harvest_fraction: 1.0``), with at
  least one gated promotion recorded, and one deliberately-poisoned
  snapshot (``HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH``) auto-demoted on
  the serving side + rolled back on the training side, finishing with
  finite loss and the incumbent bit-identically restored.
"""

import json
import random
import threading
import time
import types

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.flywheel.harvest import HarvestError, HarvestRecorder
from handyrl_tpu.flywheel.ingest import HarvestIngestor
from handyrl_tpu.flywheel.quality import (
    QualityController,
    QualityLedger,
    read_rollback_signal,
    serving_pinned_epochs,
    write_rollback_signal,
    write_serving_state,
)
from handyrl_tpu.runtime import codec
from handyrl_tpu.runtime.batch import make_batch
from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot
from handyrl_tpu.runtime.generation import Generator
from handyrl_tpu.runtime.replay import EpisodeStore
from handyrl_tpu.utils import softmax
from handyrl_tpu.utils.retry import retry_call

pytestmark = pytest.mark.flywheel


def _targs(**over):
    base = {"mesh": {"dp": 1}}
    base.update(over)
    cfg = normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": base})
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    return args


def _gen_args(targs=None):
    """The finalize-relevant subset serve_main hands the recorder."""
    targs = targs or _targs()
    return {
        "gamma": targs["gamma"],
        "compress_steps": targs["compress_steps"],
        "observation": targs["observation"],
        "obs_int8": bool(targs.get("obs_int8", False)),
    }


class _DetModel:
    """Deterministic fixed-weight policy/value head: pure function of the
    observation, so the self-play and harvest paths see byte-identical
    outputs for the same trajectory."""

    def __init__(self, seed=7):
        rng = np.random.RandomState(seed)
        self.W = rng.randn(27, 9).astype(np.float32)

    def inference(self, obs, hidden=None):
        flat = np.asarray(obs, np.float32).reshape(-1)
        logits = np.tanh(flat @ self.W).astype(np.float32)
        value = np.asarray([np.tanh(float(flat.sum()))], np.float32)
        return {"policy": logits, "value": value, "hidden": None}

    def init_hidden(self):
        return None


# ---------------------------------------------------------------------------
# parity: served session == self-play episode, bit for bit
# ---------------------------------------------------------------------------


def _selfplay_episode(seed, targs, model_id=7):
    env = make_env({"env": "TicTacToe"})
    model = _DetModel()
    players = env.players()
    random.seed(seed)
    return Generator(env, targs).generate(
        {p: model for p in players},
        {"player": players, "model_id": {p: model_id for p in players}},
    )


def _harvest_episode(seed, targs, served=7, recorder=None):
    """The SAME trajectory re-played through the serving-side capture
    seams (capture_request/capture_reply/step/close) — identical random
    stream, identical deterministic model, so the recorder sees exactly
    the requests a scripted client would have made."""
    env = make_env({"env": "TicTacToe"})
    model = _DetModel()
    rec = recorder or HarvestRecorder(_gen_args(targs))
    players = env.players()
    sids = {p: f"parity-s{p}" for p in players}
    hid = rec.open_episode(players, [sids[p] for p in players])
    random.seed(seed)
    env.reset()
    while not env.terminal():
        turn_players = env.turns()
        actions = [None] * len(players)
        legal_lists = [None] * len(players)
        moves = {}
        for p in turn_players:
            j = players.index(p)
            obs = env.observation(p)
            rec.capture_request(sids[p], obs)
            out = model.inference(obs)
            rec.capture_reply(
                sids[p], served, {"policy": out["policy"], "value": out["value"]}
            )
            logits = np.asarray(out["policy"], np.float32)
            legal = env.legal_actions(p)
            amask = np.full_like(logits, 1e32)
            amask[legal] = 0.0
            probs = softmax(logits - amask)
            action = random.choices(legal, weights=probs[legal])[0]
            actions[j] = int(action)
            legal_lists[j] = list(legal)
            moves[p] = action
        turn = turn_players[0] if turn_players else None
        env.step(moves)
        reward = env.reward()
        rec.step(hid, actions, legal_lists, [reward.get(p) for p in players], turn)
    outcome = env.outcome()
    return rec.close(hid, [float(outcome.get(p, 0.0)) for p in players])


@pytest.mark.parametrize("seed", [11, 23, 37])
def test_harvested_episode_bit_identical_to_selfplay(seed):
    targs = _targs()
    ep_self = _selfplay_episode(seed, targs)
    ep_harv = _harvest_episode(seed, targs)
    assert ep_self is not None and ep_harv is not None

    assert ep_harv["steps"] == ep_self["steps"]
    assert ep_harv["players"] == ep_self["players"]
    assert ep_harv["outcome"] == ep_self["outcome"]
    # THE bit-identity claim: the compressed column blocks are the same
    # bytes — same obs, probs, amasks, actions, values, returns, masks
    assert ep_harv["blocks"] == ep_self["blocks"]

    # ... and they stay the same bytes through the wire codec the
    # harvest_pull endpoint ships them over
    wire = codec.loads(codec.dumps(ep_harv))
    assert wire["blocks"] == ep_self["blocks"]
    assert wire["steps"] == ep_self["steps"]

    # harvest provenance stamps (never part of the block bytes)
    assert ep_harv["args"]["harvest"] is True
    assert ep_harv["model_epoch"] == 7
    assert ep_harv["args"]["model_id"] == {p: 7 for p in ep_self["players"]}


def test_harvest_ring_ingest_matches_make_batch(monkeypatch):
    """Harvested blobs through EpisodeStore -> sample_window -> make_batch
    must equal the self-play path key by key (ISSUE 6 parity style)."""
    import jax

    targs = _targs(batch_size=4, forward_steps=4, burn_in_steps=0)
    seeds = (101, 202, 303)
    eps_self = [_selfplay_episode(s, targs) for s in seeds]
    eps_harv = [_harvest_episode(s, targs) for s in seeds]
    store_s, store_h = EpisodeStore(64), EpisodeStore(64)
    store_s.extend(eps_self)
    store_h.extend(eps_harv)

    fwd, burn, cs = (
        targs["forward_steps"], targs["burn_in_steps"], targs["compress_steps"]
    )
    random.seed(9)
    win_s = [store_s.sample_window(fwd, burn, cs) for _ in range(8)]
    random.seed(9)
    win_h = [store_h.sample_window(fwd, burn, cs) for _ in range(8)]
    assert all(w is not None for w in win_s + win_h)

    monkeypatch.setattr(
        "handyrl_tpu.runtime.batch.random.randrange", lambda n: 0
    )
    batch_s = make_batch(win_s, targs)
    batch_h = make_batch(win_h, targs)

    assert set(batch_s) == set(batch_h)
    for key in batch_s:
        leaves_s = jax.tree.leaves(batch_s[key])
        leaves_h = jax.tree.leaves(batch_h[key])
        assert len(leaves_s) == len(leaves_h), key
        for ls, lh in zip(leaves_s, leaves_h):
            np.testing.assert_array_equal(
                np.asarray(lh), np.asarray(ls), err_msg=key
            )


# ---------------------------------------------------------------------------
# HarvestRecorder guards (server side)
# ---------------------------------------------------------------------------


def _open_pair(rec):
    return rec.open_episode([0, 1], ["sa", "sb"])


def _valid_row(rec, hid, sid="sa", player_slot=0, n_players=2):
    obs = np.zeros((3, 3, 3), np.float32)
    rec.capture_request(sid, obs)
    rec.capture_reply(
        sid, 3,
        {"policy": np.zeros(9, np.float32), "value": np.asarray([0.1], np.float32)},
    )
    actions = [None] * n_players
    legal = [None] * n_players
    actions[player_slot] = 0
    legal[player_slot] = [0, 1]
    rec.step(hid, actions, legal, [None] * n_players, player_slot)


def test_recorder_open_validation_and_unknown_hid():
    rec = HarvestRecorder(_gen_args())
    with pytest.raises(HarvestError):
        rec.open_episode([], [])
    with pytest.raises(HarvestError):
        rec.open_episode([0, 1], ["only-one"])
    with pytest.raises(HarvestError):
        rec.step("h999", [0], [[0]], [None], 0)
    with pytest.raises(HarvestError):
        rec.close("h999", [1.0])


def test_recorder_step_arity_mismatch_drops_episode(capsys):
    rec = HarvestRecorder(_gen_args())
    hid = _open_pair(rec)
    _valid_row(rec, hid)
    rec.step(hid, [0], [[0]], [None], 0)  # 1 != 2 players
    assert rec.close(hid, [1.0, -1.0]) is None
    assert rec.stats()["flywheel_dropped_malformed"] == 1
    assert rec.stats()["flywheel_episodes"] == 0
    assert "malformed" in capsys.readouterr().out


def test_recorder_action_without_captured_policy_drops_episode():
    rec = HarvestRecorder(_gen_args())
    hid = _open_pair(rec)
    # the client reports an action the server never inferred: the prob
    # would be a fabrication — poison for the importance weights
    rec.step(hid, [0, None], [[0, 1], None], [None, None], 0)
    assert rec.close(hid, [1.0, -1.0]) is None
    assert rec.stats()["flywheel_dropped_malformed"] == 1


def test_recorder_truncated_drops():
    rec = HarvestRecorder(_gen_args())

    hid = _open_pair(rec)
    _valid_row(rec, hid)
    assert rec.close(hid, None) is None  # outcome missing

    hid = rec.open_episode([0, 1], ["sc", "sd"])
    _valid_row(rec, hid, sid="sc")
    assert rec.close(hid, [1.0]) is None  # outcome mis-sized

    hid = rec.open_episode([0, 1], ["se", "sf"])
    assert rec.close(hid, [1.0, -1.0]) is None  # zero rows

    stats = rec.stats()
    assert stats["flywheel_dropped_truncated"] == 3
    assert stats["flywheel_episodes"] == 0


def test_recorder_ttl_sweep_drops_abandoned_sessions():
    rec = HarvestRecorder(_gen_args(), ttl_s=5.0)
    hid = _open_pair(rec)
    assert rec.sweep(now=time.monotonic() + 1.0) == 0
    assert rec.sweep(now=time.monotonic() + 60.0) == 1
    with pytest.raises(HarvestError):
        rec.close(hid, [1.0, -1.0])
    assert rec.stats()["flywheel_dropped_truncated"] == 1
    assert rec.stats()["flywheel_open"] == 0


def test_recorder_max_open_sheds_oldest():
    rec = HarvestRecorder(_gen_args(), max_open=2)
    h1 = rec.open_episode([0], ["m1"])
    rec.open_episode([0], ["m2"])
    rec.open_episode([0], ["m3"])  # sheds h1, the oldest
    assert rec.stats()["flywheel_open"] == 2
    assert rec.stats()["flywheel_dropped_truncated"] == 1
    with pytest.raises(HarvestError):
        rec.close(h1, [1.0])


def test_recorder_pull_transfers_ownership_and_counts():
    rec = HarvestRecorder(_gen_args())
    for sid in ("p1", "p2"):
        hid = rec.open_episode([0], [sid])
        _valid_row(rec, hid, sid=sid, n_players=1)
        ep = rec.close(hid, [1.0])
        assert ep is not None and ep["steps"] == 1 and ep["blocks"]

    eps, counts = rec.pull(max_episodes=1)
    assert len(eps) == 1 and counts["flywheel_queued"] == 1
    eps2, counts = rec.pull(max_episodes=8)
    assert len(eps2) == 1 and counts["flywheel_queued"] == 0
    assert rec.pull()[0] == []
    stats = rec.stats()
    assert stats["flywheel_pulled"] == 2 and stats["flywheel_episodes"] == 2


# ---------------------------------------------------------------------------
# HarvestIngestor guards (learner side)
# ---------------------------------------------------------------------------


def _blob(epoch):
    return {"args": {}, "steps": 1, "players": [0], "outcome": {0: 1.0},
            "blocks": [b""], "model_epoch": epoch}


def _ingestor(fraction, update_episodes, staleness=4, epoch_box=None):
    epoch_box = epoch_box if epoch_box is not None else [10]
    got = []
    ing = HarvestIngestor(
        {"harvest_fraction": fraction, "update_episodes": update_episodes,
         "staleness_epochs": staleness, "harvest_poll_s": 0.01,
         "harvest_max_pull": 8},
        submit=got.extend,
        current_epoch=lambda: epoch_box[0],
        make_client=lambda: None,
    )
    return ing, got, epoch_box


def test_ingest_drops_malformed_blobs(capsys):
    ing, got, _ = _ingestor(1.0, 0)
    n = ing.ingest([{"bogus": 1}, "not-even-a-dict", _blob(10)])
    assert n == 1 and len(got) == 1
    assert ing.stats()["flywheel_ingest_malformed"] == 2
    assert "malformed" in capsys.readouterr().out


def test_ingest_staleness_boundary():
    ing, got, _ = _ingestor(1.0, 0, staleness=4)  # current epoch 10
    assert ing.ingest([_blob(6)]) == 0   # 10 - 6 >= 4: stale
    assert ing.ingest([_blob(7)]) == 1   # one inside the bound
    assert ing.stats()["flywheel_ingest_stale"] == 1
    assert [e["model_epoch"] for e in got] == [7]


def test_ingest_budget_defers_over_fraction_to_next_epoch():
    ing, got, epoch = _ingestor(0.5, 8, staleness=100, epoch_box=[5])
    assert ing.epoch_budget == 4
    assert ing.ingest([_blob(5) for _ in range(6)]) == 4   # budget for epoch 5
    assert len(got) == 4
    assert ing.ingest([_blob(5)]) == 0                     # budget exhausted
    epoch[0] = 6
    assert ing.ingest([]) == 3                             # deferred re-enter
    assert len(got) == 7
    assert ing.stats()["flywheel_ingested"] == 7


def test_ingest_full_fraction_is_unthrottled():
    ing, got, _ = _ingestor(1.0, 8)
    assert ing.epoch_budget is None
    assert ing.ingest([_blob(10) for _ in range(50)]) == 50
    assert len(got) == 50


# ---------------------------------------------------------------------------
# quality plane: ledger, promotion gate, sentinel, signal files
# ---------------------------------------------------------------------------


class _StubRouter:
    """Routing-table-only double for ModelRouter's gate surface."""

    def __init__(self, template):
        self._template = template
        self._latest = None
        self._candidate = None
        self._incumbent = None
        self.staged = []
        self.refreshed = None

    def latest_id(self):
        return self._latest

    def candidate_id(self):
        return self._candidate

    def incumbent_id(self):
        return self._incumbent

    def _params_template(self):
        return self._template

    def stage(self, model_id, params, warm=True):
        self._candidate = int(model_id)
        self.staged.append((int(model_id), params))

    def promote_candidate(self):
        self._incumbent, self._latest = self._latest, self._candidate
        self._candidate = None
        return self._latest

    def demote_candidate(self):
        demoted, self._candidate = self._candidate, None
        return demoted

    def demote_latest(self):
        bad = self._latest
        self._latest, self._incumbent = self._incumbent, None
        return bad

    def maybe_refresh(self):
        return self.refreshed


def _qc(tmp_path, router, **over):
    cfg = {"gate_promotions": True, "promote_winrate": 0.6,
           "promote_games": 4, "quality_window": 3, "demote_drop": 0.1}
    cfg.update(over)
    return QualityController(router, str(tmp_path), cfg)


def _save(tmp_path, epoch, fill):
    save_epoch_snapshot(
        str(tmp_path), epoch, {"w": np.full((2, 2), fill, np.float32)},
        {"test": 0}, 0,
    )


def test_gate_stages_then_promotes_on_live_wins(tmp_path):
    router = _StubRouter({"w": np.zeros((2, 2), np.float32)})
    qc = _qc(tmp_path, router)
    _save(tmp_path, 1, 1.0)

    assert qc.tick() == "staged candidate epoch 1"
    assert router.candidate_id() == 1
    np.testing.assert_array_equal(
        router.staged[0][1]["w"], np.full((2, 2), 1.0, np.float32)
    )
    assert qc.tick() is None  # verdict needs promote_games on the books

    for outcome in (1.0, 1.0, 1.0, -1.0):  # wp 0.75 >= 0.6
        qc.record_outcome(1, outcome)
    event = qc.tick()
    assert event is not None and event.startswith("promoted epoch 1")
    assert router.latest_id() == 1 and router.candidate_id() is None
    assert qc.stats_record()["quality_promotions"] == 1
    # SERVING.json pins the live route for gc_snapshots
    assert serving_pinned_epochs(str(tmp_path)) == {1}


def test_gate_failure_demotes_signals_and_never_restages(tmp_path):
    router = _StubRouter({"w": np.zeros((2, 2), np.float32)})
    router._latest = 5
    qc = _qc(tmp_path, router)
    _save(tmp_path, 6, 6.0)

    assert qc.tick() == "staged candidate epoch 6"
    for _ in range(4):
        qc.record_outcome(6, -1.0)  # wp 0.0 < 0.6
    event = qc.tick()
    assert event is not None and event.startswith("gate failed for epoch 6")
    assert router.candidate_id() is None and router.latest_id() == 5

    sig = read_rollback_signal(str(tmp_path))
    assert sig == {"seq": 1, "bad_epoch": 6, "target_epoch": 5,
                   "reason": "gate_failed"}
    # a rejected epoch never comes back as a candidate
    assert qc.tick() is None
    assert len(router.staged) == 1
    assert qc.stats_record()["quality_gate_failures"] == 1


def test_quality_sentinel_demotes_regressed_promotion(tmp_path):
    router = _StubRouter({"w": np.zeros((2, 2), np.float32)})
    router._latest = 1
    qc = _qc(tmp_path, router, promote_games=2)
    for _ in range(3):
        qc.record_outcome(1, 1.0)  # incumbent baseline EMA = 1.0
    _save(tmp_path, 2, 2.0)

    assert qc.tick() == "staged candidate epoch 2"
    qc.record_outcome(2, 1.0)
    qc.record_outcome(2, 1.0)
    event = qc.tick()
    assert event is not None and event.startswith("promoted epoch 2")
    assert router.incumbent_id() == 1

    # live quality craters past quality_window games: EMA sinks under
    # baseline - demote_drop and the sentinel restores the incumbent
    for _ in range(3):
        qc.record_outcome(2, -1.0)
    event = qc.tick()
    assert event is not None and "demoted epoch 2" in event
    assert "restored incumbent 1" in event
    assert router.latest_id() == 1
    sig = read_rollback_signal(str(tmp_path))
    assert sig["bad_epoch"] == 2 and sig["target_epoch"] == 1
    assert sig["reason"] == "quality_regression"
    assert qc.stats_record()["quality_demotions"] == 1
    # demoted epochs are rejected: the stale snapshot never re-stages
    assert qc.tick() is None and router.candidate_id() is None


def test_quality_sentinel_watch_is_a_bounded_canary(tmp_path):
    """A promotion that holds its quality through 4 EMA windows of live
    games is CONFIRMED — later noise can never demote it.  An unbounded
    watch would eventually demote every promotion (an EMA random-walks
    below any sub-mean bar given enough games), each time costing a
    training-side rollback."""
    router = _StubRouter({"w": np.zeros((2, 2), np.float32)})
    router._latest = 1
    qc = _qc(tmp_path, router, promote_games=2, quality_window=3)
    for _ in range(3):
        qc.record_outcome(1, 1.0)
    _save(tmp_path, 2, 2.0)
    assert qc.tick() == "staged candidate epoch 2"
    qc.record_outcome(2, 1.0)
    qc.record_outcome(2, 1.0)
    assert qc.tick().startswith("promoted epoch 2")

    # 4 * quality_window healthy games confirm the promotion ...
    for _ in range(12):
        qc.record_outcome(2, 1.0)
        assert qc.tick() is None
    # ... after which even a catastrophic losing streak cannot demote
    for _ in range(20):
        qc.record_outcome(2, -1.0)
        assert qc.tick() is None
    assert router.latest_id() == 2
    assert qc.stats_record()["quality_demotions"] == 0
    assert read_rollback_signal(str(tmp_path)) is None


def test_gate_off_degrades_to_immediate_refresh(tmp_path):
    router = _StubRouter({"w": np.zeros((2, 2), np.float32)})
    router.refreshed = 4
    qc = _qc(tmp_path, router, gate_promotions=False)
    assert qc.tick() == "published epoch 4"
    assert router.staged == []


def test_ledger_ignores_fresh_init_and_counts_its_own_games():
    ledger = QualityLedger(window=8)
    ledger.record(0, 1.0)   # id 0 is the fresh-init route, not a snapshot
    ledger.record(-1, 1.0)
    assert ledger.total_games() == 0
    ledger.record(2, 1.0)
    ledger.record(2, -1.0)
    assert ledger.total_games() == 2
    assert ledger.games(2) == 2
    assert ledger.win_points(2) == pytest.approx(0.5)
    assert ledger.snapshot()["quality_wp2"] == pytest.approx(0.5)
    assert 0.0 < ledger.ema(2) < 1.0


def test_record_outcome_rejects_garbage(tmp_path):
    qc = _qc(tmp_path, _StubRouter({"w": np.zeros(1, np.float32)}))
    with pytest.raises(ValueError):
        qc.record_outcome("five", "lost")


def test_rollback_signal_seq_is_monotone(tmp_path):
    assert read_rollback_signal(str(tmp_path)) is None
    assert write_rollback_signal(str(tmp_path), 3, 2, "gate_failed") == 1
    assert write_rollback_signal(str(tmp_path), 5, 4, "quality_regression") == 2
    sig = read_rollback_signal(str(tmp_path))
    assert sig["seq"] == 2 and sig["bad_epoch"] == 5


def test_serving_pinned_epochs_filters_non_snapshots(tmp_path):
    assert serving_pinned_epochs(str(tmp_path)) == set()
    write_serving_state(str(tmp_path), 3, None, 2)
    assert serving_pinned_epochs(str(tmp_path)) == {3, 2}
    write_serving_state(str(tmp_path), 0, -1, 4)
    assert serving_pinned_epochs(str(tmp_path)) == {4}


# ---------------------------------------------------------------------------
# transient-fault retry discipline (utils/retry.py + its two call sites)
# ---------------------------------------------------------------------------


def test_retry_backoff_schedule_then_success():
    calls, sleeps, retries = [], [], []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("flake")
        return 7

    got = retry_call(fn, attempts=3, base_delay=0.1, factor=2.0,
                     max_delay=0.15, sleep=sleeps.append,
                     on_retry=lambda i, exc: retries.append(i))
    assert got == 7 and len(calls) == 3
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.15)]  # capped
    assert retries == [0, 1]


def test_retry_exhaustion_raises_the_last_error():
    calls = []

    def fn():
        calls.append(1)
        raise TimeoutError(f"try {len(calls)}")

    with pytest.raises(TimeoutError, match="try 3"):
        retry_call(fn, attempts=2, base_delay=0.0, sleep=lambda s: None)
    assert len(calls) == 3  # first try + 2 retries


def test_retry_non_retryable_propagates_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("logic bug, not a flake")

    with pytest.raises(ValueError):
        retry_call(fn, attempts=5, base_delay=0.0, sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_attempts_zero_is_a_single_try():
    calls = []

    def fn():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(fn, attempts=0, sleep=lambda s: None)
    assert len(calls) == 1


def test_actor_host_reconnect_shape_reissues_same_request():
    """The actor-host poll seam, socket-free: a wedged client fails the
    call, on_retry swaps in a freshly-dialed client, and retry_call
    re-issues the SAME request against it (actor_host.py _reconnect)."""

    class _Wedged:
        polls = 0
        closed = False

        def poll_params(self):
            self.polls += 1
            raise ConnectionError("reset mid-frame")

        def close(self):
            self.closed = True

    class _Healthy:
        polls = 0

        def poll_params(self):
            self.polls += 1
            return (3, {"w": 1})

    wedged, healthy = _Wedged(), _Healthy()
    client = wedged

    def _reconnect(i, exc):
        nonlocal client
        client.close()
        client = healthy

    got = retry_call(lambda: client.poll_params(), attempts=3,
                     base_delay=0.0, sleep=lambda s: None,
                     on_retry=_reconnect)
    assert got == (3, {"w": 1})
    assert wedged.polls == 1 and wedged.closed
    assert healthy.polls == 1


def _bare_fleet_router(attempts=2):
    from handyrl_tpu.fleet.router_tier import FleetRouter

    fr = FleetRouter.__new__(FleetRouter)
    fr.poll_retry_attempts = attempts
    fr.poll_retry_backoff_s = 0.001
    fr.stats_poll_s = 0.01
    fr._stats_lock = threading.Lock()
    fr.poll_retries = 0
    return fr


def test_fleet_stats_poll_retries_transient_faults():
    fr = _bare_fleet_router(attempts=2)
    n = [0]

    class _FlakyClient:
        def stats(self, timeout=None):
            n[0] += 1
            if n[0] < 3:
                raise ConnectionError("storm")
            return {"serve_models": 1}

    got = fr._replica_stats(types.SimpleNamespace(client=_FlakyClient()))
    assert got == {"serve_models": 1}
    assert n[0] == 3 and fr.poll_retries == 2


def test_fleet_stats_poll_exhausts_then_raises():
    fr = _bare_fleet_router(attempts=1)

    class _DeadClient:
        def stats(self, timeout=None):
            raise TimeoutError("gone")

    with pytest.raises(TimeoutError):
        fr._replica_stats(types.SimpleNamespace(client=_DeadClient()))
    assert fr.poll_retries == 1


def test_fleet_stats_poll_server_reported_error_never_retries():
    from handyrl_tpu.serving import ServingError

    fr = _bare_fleet_router(attempts=5)
    n = [0]

    class _Misbehaving:
        def stats(self, timeout=None):
            n[0] += 1
            raise ServingError("bad_request", "peer misbehaving")

    with pytest.raises(ServingError):
        fr._replica_stats(types.SimpleNamespace(client=_Misbehaving()))
    assert n[0] == 1 and fr.poll_retries == 0


def test_fleet_stats_poll_clientless_replica_is_connection_error():
    fr = _bare_fleet_router()
    with pytest.raises(ConnectionError):
        fr._replica_stats(types.SimpleNamespace(client=None))
    assert fr.poll_retries == 0


# ---------------------------------------------------------------------------
# config validation pins
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("knob, value, match", [
    ("harvest_fraction", 1.5, "must be in \\[0, 1\\]"),
    ("staleness_epochs", 0, "staleness_epochs"),
    ("promote_winrate", 1.0, "must be in \\(0, 1\\)"),
    ("harvest_port", "9997", "TCP port"),
    ("harvest_poll_s", 0.0, "must be > 0"),
    ("gate_promotions", 1, "must be a bool"),
])
def test_flywheel_config_validation(knob, value, match):
    with pytest.raises(ValueError, match=match):
        normalize_args({
            "env_args": {"env": "TicTacToe"},
            "train_args": {"flywheel": {knob: value}},
        })


@pytest.mark.parametrize("knob, value", [
    ("poll_retry_attempts", -1),
    ("poll_retry_backoff_s", 0.0),
])
def test_fleet_retry_config_validation(knob, value):
    with pytest.raises(ValueError, match=knob):
        normalize_args({
            "env_args": {"env": "TicTacToe"},
            "train_args": {"fleet": {knob: value}},
        })


# ---------------------------------------------------------------------------
# flagship e2e: serve + train on served traffic only, gated promotions,
# poisoned-snapshot auto-demote + training-side rollback
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_flywheel_e2e_served_traffic_trains_gates_and_rolls_back(
        tmp_path, monkeypatch, capsys):
    import jax

    from handyrl_tpu.flywheel import FlywheelPlane
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.checkpoint import load_verified_params
    from handyrl_tpu.runtime.learner import Learner
    from handyrl_tpu.serving import ModelRouter, ServingClient, ServingError, ServingServer

    monkeypatch.chdir(tmp_path)
    EPOCHS, UPDATE_EPISODES, POISON = 30, 120, 20
    monkeypatch.setenv("HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH", str(POISON))

    fly_cfg = {
        "enabled": True,
        "harvest_fraction": 1.0,      # served traffic ONLY — zero self-play
        "staleness_epochs": 8,
        "harvest_poll_s": 0.1,
        "harvest_max_pull": 256,
        "gate_promotions": True,
        "promote_winrate": 0.35,      # clean snapshots clear this vs random
        "promote_games": 12,          # verdicts resolve inside one epoch
        "quality_window": 16,         # canary confirms after 64 live games
        "demote_drop": 0.25,
        "shadow_fraction": 1.0,       # all default-route traffic shadows the
                                      # candidate: clean outcome attribution
    }
    serving_cfg = {
        "port": 0, "max_models": 4, "slo_ms": 2000.0, "shed_policy": "none",
        "max_batch": 64, "max_wait_ms": 1.0, "warm_buckets": [1, 2, 4, 8, 16],
        "queue_bound": 8192, "recv_timeout": 0.0, "watch_interval": 0.2,
        "stats_interval": 0.0,
    }

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    env.reset()
    obs0 = env.observation(0)
    template = init_variables(module, env, seed=0)["params"]

    # serving side first: the cold router serves a fresh-init net under
    # id 0 (serve_main's cold start) so clients have traffic from t=0
    router = ModelRouter(module, obs0, serving_cfg, model_dir="models")
    router.publish(0, template)
    targs_probe = _targs()
    flywheel = FlywheelPlane(router, "models", fly_cfg, _gen_args(targs_probe))
    server = ServingServer(router, serving_cfg, flywheel=flywheel).run()

    stop = threading.Event()
    books = []          # (eval outcomes for the served side, time-ordered)
    books_lock = threading.Lock()
    client_errors = []

    def _harvest_game(client, game_env, rng):
        players = game_env.players()
        sids = [client.open_session() for _ in players]
        hid = client.harvest_open(players, sids)
        game_env.reset()
        while not game_env.terminal():
            turn_players = game_env.turns()
            actions = [None] * len(players)
            legal_lists = [None] * len(players)
            moves = {}
            for p in turn_players:
                j = players.index(p)
                reply = client.infer(game_env.observation(p), sid=sids[j])
                logits = np.asarray(reply["out"]["policy"], np.float32).reshape(-1)
                legal = list(game_env.legal_actions(p))
                amask = np.full_like(logits, 1e32)
                amask[legal] = 0.0
                probs = softmax(logits - amask)
                action = rng.choices(
                    legal, weights=[float(probs[a]) for a in legal]
                )[0]
                actions[j] = int(action)
                legal_lists[j] = legal
                moves[p] = int(action)
            turn = turn_players[0] if turn_players else None
            game_env.step(moves)
            reward = game_env.reward()
            client.harvest_step(
                hid, actions, legal_lists,
                [reward.get(p) for p in players], turn,
            )
        outcome = game_env.outcome()
        client.harvest_close(hid, [float(outcome.get(p, 0.0)) for p in players])
        for sid in sids:
            client.close_session(sid)

    def _eval_game(client, game_env, rng, seat):
        """Served (greedy) vs scripted-random, alternating seats; the
        outcome lands on the served snapshot's live books."""
        game_env.reset()
        served_id = None
        while not game_env.terminal():
            moves = {}
            for p in game_env.turns():
                legal = list(game_env.legal_actions(p))
                if p == seat:
                    reply = client.infer(game_env.observation(p))
                    if served_id is None and isinstance(reply.get("model"), int):
                        served_id = reply["model"]
                    logits = np.asarray(reply["out"]["policy"]).reshape(-1)
                    action = max(legal, key=lambda a: (logits[a], rng.random()))
                else:
                    action = rng.choice(legal)
                moves[p] = int(action)
            game_env.step(moves)
        outcome = float(game_env.outcome().get(seat, 0.0))
        if served_id is not None and served_id > 0:
            client.report_outcome(served_id, outcome)
        with books_lock:
            books.append(outcome)

    def _client_loop(idx):
        rng = random.Random(1000 + idx)
        game_env = make_env({"env": "TicTacToe"})
        client = ServingClient("127.0.0.1", server.bound_port)
        g = 0
        try:
            while not stop.is_set():
                g += 1
                try:
                    if g % 3 == 0:
                        _eval_game(client, game_env, rng, seat=(g // 3) % 2)
                    else:
                        _harvest_game(client, game_env, rng)
                except ServingError:
                    continue  # shed/evicted mid-request during a flip
                except (ConnectionError, OSError, TimeoutError):
                    if stop.is_set():
                        return
                    time.sleep(0.1)
        except Exception as exc:  # anything else is a real bug — surface it
            client_errors.append(f"client {idx}: {type(exc).__name__}: {exc}")
        finally:
            try:
                client.close()
            except Exception:
                pass

    threads = [
        threading.Thread(target=_client_loop, args=(i,), daemon=True)
        for i in range(3)
    ]
    for t in threads:
        t.start()

    try:
        args = normalize_args({
            "env_args": {"env": "TicTacToe"},
            "train_args": {
                "batch_size": 64,
                "forward_steps": 8,
                "minimum_episodes": UPDATE_EPISODES,
                "update_episodes": UPDATE_EPISODES,
                "maximum_episodes": 3000,
                "epochs": EPOCHS,
                "num_batchers": 1,
                "worker": {"num_parallel": 0},  # self-play fraction: ZERO
                "flywheel": dict(fly_cfg, harvest_port=server.bound_port),
            },
        })
        learner = Learner(args)
        learner.run()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30.0)

    assert not client_errors, client_errors

    # -- the learner really trained, on harvested episodes only -----------
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) >= EPOCHS
    trained = [r for r in records if r.get("loss") is not None]
    assert trained, "no training epochs recorded"
    for r in trained:
        assert np.isfinite(float(r["loss"]["total"])), r["loss"]
    ingested = max(r.get("flywheel_ingested", 0) for r in records)
    assert ingested >= UPDATE_EPISODES * (EPOCHS // 2), (
        f"only {ingested} harvested episodes ingested"
    )

    # -- live win rate vs the scripted clients CLIMBS ----------------------
    assert len(books) >= 200, f"only {len(books)} eval games played"
    k = max(1, int(len(books) * 0.4))
    early = float(np.mean(books[:k]))
    late = float(np.mean(books[-k:]))
    assert late > early, (
        f"no live climb: early {early:.3f} -> late {late:.3f} "
        f"over {len(books)} eval games"
    )

    # -- >= 1 gated promotion recorded ------------------------------------
    quality = flywheel.stats_record()
    assert quality["quality_promotions"] >= 1, quality

    # -- the poisoned snapshot was auto-demoted on the serving side --------
    out = capsys.readouterr().out
    assert (f"gate failed for epoch {POISON}" in out
            or f"demoted epoch {POISON}" in out), (
        f"poisoned epoch {POISON} never demoted by the quality plane"
    )
    assert router.latest_id() != POISON
    assert router.candidate_id() != POISON

    # -- ... and rolled back on the training side --------------------------
    assert learner.flywheel_rollbacks >= 1
    assert learner.trainer.sentinel_events.get(
        "sentinel_flywheel_rollbacks", 0
    ) >= 1
    sig = read_rollback_signal("models")
    assert sig is not None and sig["seq"] >= 1

    # -- the incumbent is restored BIT-IDENTICALLY -------------------------
    latest = router.latest_id()
    assert latest is not None and latest > 0 and latest != POISON
    served_params = jax.device_get(
        router._engines[latest].model.variables["params"]
    )
    disk_params = load_verified_params("models", latest, template)
    served_leaves = jax.tree.leaves(served_params)
    disk_leaves = jax.tree.leaves(disk_params)
    assert len(served_leaves) == len(disk_leaves)
    for sl, dl in zip(served_leaves, disk_leaves):
        np.testing.assert_array_equal(np.asarray(sl), np.asarray(dl))

    server.shutdown()
