"""Parallelism tests on the 8-device virtual CPU mesh: ring attention
(sequence parallelism) golden-checked against full attention, and
tensor-parallel ('mp') parameter sharding through a real train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu.ops import full_attention_reference, ring_self_attention
from handyrl_tpu.parallel import make_mesh, param_shardings

# The ring paths' varying-type marking is a compat ladder (pcast -> pvary
# -> identity on pre-VMA jax like this container's 0.4.37, where shard_map
# has no varying types and marking is a no-op) — ops/ring_attention.py
# _ring_loop.  The former version-gated skips here are real passes on
# every branch of the ladder.


def _qkv(key, B=2, T=16, H=2, D=4):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, T, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, T, H, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("mesh_spec", [{"sp": 8}, {"dp": 2, "sp": 4}])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(mesh_spec, causal):
    mesh = make_mesh(mesh_spec)
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = ring_self_attention(q, k, v, mesh, causal=causal)
    ref = full_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_no_sp_axis_fallback():
    mesh = make_mesh({"dp": 8})
    q, k, v = _qkv(jax.random.PRNGKey(1))
    out = ring_self_attention(q, k, v, mesh, causal=True)
    ref = full_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_differentiable():
    # slow leg: the 8-shard grad compile is the expensive half of the ring
    # battery; the forward goldens above stay in tier-1, and the grad path
    # is also pinned end-to-end by test_transformer_train_step_ring_sp
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(jax.random.PRNGKey(2))

    def loss_ring(q, k, v):
        return (ring_self_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_full(q, k, v):
        return (full_attention_reference(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), rtol=1e-4, atol=1e-4)


def _masked_case(seed, B, T, H, D, observed_frac=0.7):
    q, k, v = _qkv(jax.random.PRNGKey(seed), B, T, H, D)
    km = jax.random.uniform(jax.random.PRNGKey(seed + 50), (B, T))
    key_mask = (km < observed_frac).astype(jnp.float32)
    slopes = 2.0 ** (-jnp.arange(1, H + 1, dtype=jnp.float32))
    return q, k, v, key_mask, slopes


@pytest.mark.parametrize("mesh_spec", [{"sp": 8}, {"dp": 2, "sp": 4}])
@pytest.mark.parametrize("window", [1 << 30, 6])
def test_masked_ring_attention_matches_reference(mesh_spec, window):
    """Sequence-parallel attention with the PRODUCTION transformer
    semantics (observation masks, observed-age ALiBi, window eviction) vs
    the exact einsum the einsum branch executes."""
    from handyrl_tpu.ops import masked_ring_self_attention
    from handyrl_tpu.ops.flash_attention import masked_attention_reference

    mesh = make_mesh(mesh_spec)
    q, k, v, key_mask, slopes = _masked_case(3, 2, 16, 2, 4)
    out = masked_ring_self_attention(q, k, v, key_mask, slopes, mesh, window=window)
    ref = masked_attention_reference(q, k, v, key_mask, slopes, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_masked_ring_attention_differentiable():
    from handyrl_tpu.ops import masked_ring_self_attention
    from handyrl_tpu.ops.flash_attention import masked_attention_reference

    mesh = make_mesh({"sp": 8})
    q, k, v, key_mask, slopes = _masked_case(4, 1, 16, 2, 4)

    def loss_ring(q, k, v):
        return (
            masked_ring_self_attention(q, k, v, key_mask, slopes, mesh, window=6) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            masked_attention_reference(q, k, v, key_mask, slopes, window=6) ** 2
        ).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), rtol=1e-4, atol=1e-4)


def test_masked_ring_no_sp_axis_fallback():
    from handyrl_tpu.ops import masked_ring_self_attention
    from handyrl_tpu.ops.flash_attention import masked_attention_reference

    mesh = make_mesh({"dp": 8})
    q, k, v, key_mask, slopes = _masked_case(5, 2, 16, 2, 4)
    out = masked_ring_self_attention(q, k, v, key_mask, slopes, mesh, window=6)
    ref = masked_attention_reference(q, k, v, key_mask, slopes, window=6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_param_shardings_mp_axis():
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables

    mesh = make_mesh({"dp": 4, "mp": 2})
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    params = init_variables(module, env)["params"]
    shardings = param_shardings(mesh, params)

    leaves = jax.tree.leaves(shardings)
    param_leaves = jax.tree.leaves(params)
    sharded = [
        s for s, p in zip(leaves, param_leaves)
        if np.asarray(p).ndim >= 2 and np.asarray(p).shape[-1] % 2 == 0
    ]
    assert sharded, "expected at least one mp-sharded kernel"
    assert all("mp" in (s.spec[-1] or ()) or s.spec[-1] == "mp" for s in sharded)


def _env_batch(env_args, train_overrides):
    import random

    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, RandomModel, init_variables
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    # pin the GLOBAL random stream: episode generation below draws from
    # it, and inheriting whatever state earlier in-process tests left
    # (learner/league e2es make a timing-dependent number of draws)
    # makes the numeric-tolerance tests downstream load-flaky — the bf16
    # delta bound was observed failing only under full-suite load
    random.seed(20260804)

    cfg = normalize_args(
        {
            "env_args": env_args,
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "compress_steps": 4,
                **train_overrides,
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 4:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], args["burn_in_steps"], args["compress_steps"])
        if w is not None:
            windows.append(w)
    return module, variables, make_batch(windows, args), args


def test_train_step_with_mp_mesh():
    """Full sharded train step on a dp x mp mesh ends with finite loss."""
    from handyrl_tpu.parallel import TrainContext

    module, variables, batch, args = _env_batch({"env": "TicTacToe"}, {"mesh": {"dp": 4, "mp": 2}})
    mesh = make_mesh(args["mesh"])
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(variables["params"])
    state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
    total = float(jax.device_get(metrics["total"]))
    assert np.isfinite(total)
    # params kept their tensor-parallel layout through the donated update
    kernel_shardings = [
        x.sharding.spec for x in jax.tree.leaves(state["params"]) if x.ndim >= 2
    ]
    assert any("mp" in [a for a in spec if a] for spec in kernel_shardings)


@pytest.mark.parametrize(
    "env_args,overrides",
    [
        ({"env": "TicTacToe"}, {}),                                    # feed-forward
        ({"env": "Geister"}, {"observation": True}),                   # DRC scan
        (
            {"env": "TicTacToe", "net": "transformer"},
            {"observation": True, "burn_in_steps": 2},                 # seq attention
        ),
    ],
)
def test_train_step_bfloat16(env_args, overrides):
    """bf16 compute path: finite loss close to fp32, fp32 master weights."""
    from handyrl_tpu.parallel import TrainContext

    module, variables, batch, args = _env_batch(env_args, overrides)
    mesh = make_mesh({"dp": -1})

    losses = {}
    for dtype in ("float32", "bfloat16"):
        ctx = TrainContext(module, {**args, "compute_dtype": dtype}, mesh)
        state = ctx.init_state(variables["params"])
        state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
        losses[dtype] = float(jax.device_get(metrics["total"]))
        assert np.isfinite(losses[dtype])
        assert all(
            x.dtype == np.float32
            for x in jax.tree.leaves(state["params"])
        ), "master weights must stay fp32"
    assert abs(losses["bfloat16"] - losses["float32"]) < 0.1 * (abs(losses["float32"]) + 1.0)