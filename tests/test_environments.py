"""Environment contract tests.

Same three-interface strategy as the reference (tests/test_environment.py):
property checks, full random games through the shared-env interface, and
full games driven purely through the ``diff_info``/``update`` replica
protocol (the socket-free surrogate for network battle mode), plus extra
determinism/outcome invariants the reference lacks.
"""

import random

import numpy as np
import pytest

from handyrl_tpu.envs import make_env

ENV_NAMES = [
    "TicTacToe",
    "ParallelTicTacToe",
    "Geister",
    "HungryGeese",
    # first-class zoo entry for the worked example (league/autovec bench
    # legs run against it as a registered scenario)
    "ConnectFour",
    # ...and the same module by dotted path, exercising the registry
    # fallback the way a user would (docs/custom_environment.md)
    "examples.connect_four",
]


def test_connect_four_registry_entry_is_the_example_module():
    """`env: ConnectFour` must resolve to the same Environment class as
    the documented dotted path — one module, two spellings."""
    a = make_env({"env": "ConnectFour"})
    b = make_env({"env": "examples.connect_four"})
    assert type(a) is type(b)


def _make(name):
    return make_env({"env": name})


@pytest.mark.parametrize("name", ENV_NAMES)
def test_environment_property(name):
    e = _make(name)
    players = e.players()
    assert len(players) >= 2
    str(e)
    e.reset()
    for p in e.turns():
        acts = e.legal_actions(p)
        assert len(acts) > 0
        # codecs round-trip
        for a in acts[:5]:
            assert e.str2action(e.action2str(a, p), p) == a


@pytest.mark.parametrize("name", ENV_NAMES)
def test_environment_local(name):
    random.seed(0)
    e = _make(name)
    for _ in range(100):
        e.reset()
        steps = 0
        while not e.terminal():
            actions = {p: random.choice(e.legal_actions(p)) for p in e.turns()}
            e.step(actions)
            e.reward()
            steps += 1
            assert steps < 1000, "game failed to terminate"
        outcome = e.outcome()
        assert set(outcome.keys()) == set(e.players())
        # zero-sum style outcomes
        assert abs(sum(outcome.values())) < 1e-6


@pytest.mark.parametrize("name", ENV_NAMES)
def test_environment_network(name):
    """Replica envs driven only by diff_info/update stay action-consistent."""
    random.seed(1)
    e = _make(name)
    replicas = {p: _make(name) for p in e.players()}
    for _ in range(100):
        e.reset()
        for p, rep in replicas.items():
            rep.update(e.diff_info(p), True)
        while not e.terminal():
            actions = {}
            for p in e.turns():
                assert set(e.legal_actions(p)) == set(replicas[p].legal_actions(p))
                # a replica must see exactly what the master would show it
                np.testing.assert_equal(replicas[p].observation(p), e.observation(p))
                a = random.choice(replicas[p].legal_actions(p))
                actions[p] = e.str2action(replicas[p].action2str(a, p), p)
            e.step(actions)
            for p, rep in replicas.items():
                rep.update(e.diff_info(p), False)
                # replicas must agree the game is (not) over
                assert rep.terminal() == e.terminal()
            e.reward()
        e.outcome()


@pytest.mark.parametrize("name", ENV_NAMES)
def test_observation_shape_stable(name):
    """Observations keep identical pytree structure/shape/dtype every step —
    a hard requirement for fixed-shape XLA batching."""
    import jax

    random.seed(2)
    e = _make(name)
    e.reset()
    ref_struct = jax.tree.map(lambda x: (x.shape, x.dtype), e.observation(e.players()[0]))
    for _ in range(3):
        e.reset()
        while not e.terminal():
            for p in e.players():
                struct = jax.tree.map(lambda x: (x.shape, x.dtype), e.observation(p))
                assert struct == ref_struct
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})


def test_tictactoe_known_positions():
    e = _make("TicTacToe")
    e.reset()
    # O plays 0,1,2 (top row) while X plays 3,4: O wins
    for a in [0, 3, 1, 4, 2]:
        e.play(a)
    assert e.terminal()
    assert e.outcome() == {0: 1, 1: -1}
    # X wins the middle column: O plays 0,2,6 / X plays 1,4,7
    e.reset()
    for a in [0, 1, 2, 4, 6, 7]:
        e.play(a)
    assert e.terminal()
    assert e.outcome() == {0: -1, 1: 1}
    # full-board draw: 0,1,2,4,3,5,7,6,8 alternating
    e.reset()
    for a in [0, 1, 2, 4, 3, 5, 7, 6, 8]:
        e.play(a)
    assert e.terminal()
    assert e.outcome() == {0: 0, 1: 0}


def test_geister_piece_accounting():
    random.seed(3)
    e = _make("Geister")
    for _ in range(20):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
            counts = e._piece_counts()
            total = sum(counts[0]) + sum(counts[1])
            assert total == int(e.alive.sum()) <= 16
        assert e.win_color in (0, 1, 2)


def test_hungry_geese_ranking():
    e = _make("HungryGeese")
    e.reset()
    e.rank_rewards = [400, 400, 300, 100]
    out = e.outcome()
    assert out[0] == out[1] > out[2] > out[3]
    assert abs(sum(out.values())) < 1e-9


class TestHungryGeeseRules:
    """Pin every official-interpreter rule from docs/hungry_geese_parity.md
    (kaggle_environments is not installable here, so each rule is pinned by
    a constructed position instead of a lock-step trace)."""

    def _env(self):
        e = _make("HungryGeese")
        e.reset()
        return e

    @staticmethod
    def _cell(r, c):
        return r * 11 + c

    def _setup(self, e, geese, food):
        e.geese = [list(g) for g in geese]
        e.active = [bool(g) for g in geese]
        e.food = list(food)
        e.last_actions = {}
        e.step_count = 0

    def test_reverse_death(self):
        e = self._env()
        self._setup(e, [[self._cell(3, 3)], [self._cell(0, 0)], [], []], [self._cell(6, 10)])
        e.last_actions = {0: 0}  # last moved NORTH
        e.step({0: 1, 1: 0})  # 0 reverses SOUTH -> dies
        assert not e.active[0] and e.geese[0] == []

    def test_food_growth_keeps_tail(self):
        e = self._env()
        head, tail = self._cell(3, 3), self._cell(3, 2)
        food = self._cell(2, 3)  # north of head
        self._setup(e, [[head, tail], [self._cell(6, 0)], [], []], [food, self._cell(6, 10)])
        e.step({0: 0, 1: 0})  # NORTH onto food
        assert e.geese[0] == [food, head, tail]  # grew, tail kept
        assert food not in e.food

    def test_move_without_food_pops_tail(self):
        e = self._env()
        head, tail = self._cell(3, 3), self._cell(3, 2)
        self._setup(e, [[head, tail], [self._cell(6, 0)], [], []], [self._cell(6, 10)])
        e.step({0: 0, 1: 0})
        assert e.geese[0] == [self._cell(2, 3), head]

    def test_chasing_own_tail_is_legal(self):
        """Rule 3: tail pops before the self-collision check, so moving into
        the current tail cell (not eating) is legal."""
        e = self._env()
        # 2x2 loop: head at (3,3), body (3,4), (4,4), tail (4,3); EAST... use
        # square ring and move head onto the vacating tail cell
        ring = [self._cell(3, 3), self._cell(3, 4), self._cell(4, 4), self._cell(4, 3)]
        self._setup(e, [ring, [self._cell(0, 0)], [], []], [self._cell(6, 10)])
        e.step({0: 1, 1: 0})  # SOUTH onto (4,3) = current tail
        assert e.active[0]
        assert e.geese[0] == [self._cell(4, 3), self._cell(3, 3), self._cell(3, 4), self._cell(4, 4)]

    def test_self_collision_death(self):
        e = self._env()
        # long body: moving EAST hits own body cell that does NOT vacate
        g = [self._cell(3, 3), self._cell(2, 3), self._cell(2, 4), self._cell(3, 4), self._cell(4, 4), self._cell(4, 3)]
        self._setup(e, [g, [self._cell(0, 0)], [], []], [self._cell(6, 10)])
        e.step({0: 3, 1: 0})  # EAST into (3,4)
        assert not e.active[0]

    def test_hunger_pops_tail_on_step_40(self):
        e = self._env()
        head, tail = self._cell(3, 3), self._cell(3, 2)
        self._setup(e, [[head, tail], [self._cell(6, 0)], [], []], [self._cell(6, 10)])
        e.step_count = 39  # this step becomes 40
        e.step({0: 0, 1: 0})
        assert len(e.geese[0]) == 1  # moved (pop) + hunger (pop) from 2+head

    def test_hunger_starves_length_one(self):
        e = self._env()
        self._setup(e, [[self._cell(3, 3)], [self._cell(6, 0), self._cell(6, 1)], [], []], [self._cell(0, 5)])
        e.step_count = 39
        e.step({0: 0, 1: 0})
        assert e.geese[0] == []  # shrank to zero
        assert e.geese[1]        # survived (game then ends: last goose standing)
        assert e.terminal()

    def test_head_to_head_collision_kills_both(self):
        e = self._env()
        a, b = self._cell(3, 3), self._cell(3, 5)
        self._setup(e, [[a], [b], [self._cell(0, 0)], []], [self._cell(6, 10)])
        e.step({0: 3, 1: 2, 2: 0})  # both into (3,4)
        assert e.geese[0] == [] and e.geese[1] == []
        assert e.geese[2]  # last goose standing; game ends
        assert e.terminal()

    def test_head_into_body_kills_mover_only(self):
        e = self._env()
        mover = [self._cell(3, 3)]
        wall = [self._cell(2, 4), self._cell(2, 3), self._cell(2, 2)]
        # wall moves SOUTH to (3,4); mover EAST to (3,4)? that's head-to-head.
        # Instead: mover NORTH into wall's mid-body cell (2,3) which stays.
        self._setup(e, [mover, wall, [self._cell(6, 0)], []], [self._cell(6, 10)])
        e.step({0: 0, 1: 1, 2: 0})  # wall head (2,4) SOUTH to (3,4)
        assert not e.active[0]
        assert e.active[1]

    def test_shared_food_lower_index_eats_both_die(self):
        e = self._env()
        food = self._cell(3, 4)
        self._setup(e, [[self._cell(3, 3)], [self._cell(3, 5)], [self._cell(0, 0)], []], [food, self._cell(6, 10)])
        e.step({0: 3, 1: 2, 2: 0})
        assert food not in e.food  # removed exactly once
        assert not e.active[0] and not e.active[1]

    def test_dead_goose_keeps_previous_reward(self):
        """Rule 9: rewards update only for survivors, after deaths."""
        e = self._env()
        self._setup(e, [[self._cell(3, 3)], [self._cell(0, 0)], [self._cell(6, 5)], []], [self._cell(6, 10)])
        e.rank_rewards = [101, 101, 101, 101]
        e.last_actions = {0: 0}
        e.step({0: 1, 1: 0, 2: 0})  # goose 0 reverses and dies
        assert e.rank_rewards[0] == 101          # frozen at pre-death value
        assert e.rank_rewards[1] == 2 * 100 + 1  # (t+1)*scale + len
        # survival beats the dead goose in the final ranking
        assert e.rank_rewards[1] > e.rank_rewards[0]

    def test_food_respawns_to_min(self):
        e = self._env()
        self._setup(e, [[self._cell(3, 3)], [self._cell(0, 0)], [], []], [self._cell(3, 4)])
        e.step({0: 3, 1: 0})  # eat the only food
        assert len(e.food) == 2  # respawned to MIN_FOOD
        occupied = {c for g in e.geese for c in g}
        assert not (set(e.food) & occupied)

    def test_episode_step_limit(self):
        e = self._env()
        self._setup(e, [[self._cell(0, 0)], [self._cell(3, 3)], [self._cell(5, 5)], []], [self._cell(6, 10)])
        e.step_count = 198
        e.step({0: 0, 1: 0, 2: 0})
        assert e.terminal()  # 199 transitions = kaggle episodeSteps 200


def test_observation_viewpoint_rotation():
    """Geister: White's observation is the 180-rotation of the board."""
    random.seed(4)
    e = _make("Geister")
    e.reset()
    e.play(144)  # black layout 0
    e.play(144)  # white layout 0
    obs_b = e.observation(0)
    obs_w = e.observation(1)
    assert obs_b["board"].shape == (7, 6, 6)
    assert obs_w["board"].shape == (7, 6, 6)
    # plane 1 is "my pieces": white's own pieces rotated must equal black's view of white pieces
    np.testing.assert_allclose(
        np.rot90(obs_w["board"][1], k=2, axes=(0, 1)), obs_b["board"][2]
    )
