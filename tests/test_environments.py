"""Environment contract tests.

Same three-interface strategy as the reference (tests/test_environment.py):
property checks, full random games through the shared-env interface, and
full games driven purely through the ``diff_info``/``update`` replica
protocol (the socket-free surrogate for network battle mode), plus extra
determinism/outcome invariants the reference lacks.
"""

import random

import numpy as np
import pytest

from handyrl_tpu.envs import make_env

ENV_NAMES = ["TicTacToe", "ParallelTicTacToe", "Geister", "HungryGeese"]


def _make(name):
    return make_env({"env": name})


@pytest.mark.parametrize("name", ENV_NAMES)
def test_environment_property(name):
    e = _make(name)
    players = e.players()
    assert len(players) >= 2
    str(e)
    e.reset()
    for p in e.turns():
        acts = e.legal_actions(p)
        assert len(acts) > 0
        # codecs round-trip
        for a in acts[:5]:
            assert e.str2action(e.action2str(a, p), p) == a


@pytest.mark.parametrize("name", ENV_NAMES)
def test_environment_local(name):
    random.seed(0)
    e = _make(name)
    for _ in range(100):
        e.reset()
        steps = 0
        while not e.terminal():
            actions = {p: random.choice(e.legal_actions(p)) for p in e.turns()}
            e.step(actions)
            e.reward()
            steps += 1
            assert steps < 1000, "game failed to terminate"
        outcome = e.outcome()
        assert set(outcome.keys()) == set(e.players())
        # zero-sum style outcomes
        assert abs(sum(outcome.values())) < 1e-6


@pytest.mark.parametrize("name", ENV_NAMES)
def test_environment_network(name):
    """Replica envs driven only by diff_info/update stay action-consistent."""
    random.seed(1)
    e = _make(name)
    replicas = {p: _make(name) for p in e.players()}
    for _ in range(100):
        e.reset()
        for p, rep in replicas.items():
            rep.update(e.diff_info(p), True)
        while not e.terminal():
            actions = {}
            for p in e.turns():
                assert set(e.legal_actions(p)) == set(replicas[p].legal_actions(p))
                # a replica must see exactly what the master would show it
                np.testing.assert_equal(replicas[p].observation(p), e.observation(p))
                a = random.choice(replicas[p].legal_actions(p))
                actions[p] = e.str2action(replicas[p].action2str(a, p), p)
            e.step(actions)
            for p, rep in replicas.items():
                rep.update(e.diff_info(p), False)
                # replicas must agree the game is (not) over
                assert rep.terminal() == e.terminal()
            e.reward()
        e.outcome()


@pytest.mark.parametrize("name", ENV_NAMES)
def test_observation_shape_stable(name):
    """Observations keep identical pytree structure/shape/dtype every step —
    a hard requirement for fixed-shape XLA batching."""
    import jax

    random.seed(2)
    e = _make(name)
    e.reset()
    ref_struct = jax.tree.map(lambda x: (x.shape, x.dtype), e.observation(e.players()[0]))
    for _ in range(3):
        e.reset()
        while not e.terminal():
            for p in e.players():
                struct = jax.tree.map(lambda x: (x.shape, x.dtype), e.observation(p))
                assert struct == ref_struct
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})


def test_tictactoe_known_positions():
    e = _make("TicTacToe")
    e.reset()
    # O plays 0,1,2 (top row) while X plays 3,4: O wins
    for a in [0, 3, 1, 4, 2]:
        e.play(a)
    assert e.terminal()
    assert e.outcome() == {0: 1, 1: -1}
    # X wins the middle column: O plays 0,2,6 / X plays 1,4,7
    e.reset()
    for a in [0, 1, 2, 4, 6, 7]:
        e.play(a)
    assert e.terminal()
    assert e.outcome() == {0: -1, 1: 1}
    # full-board draw: 0,1,2,4,3,5,7,6,8 alternating
    e.reset()
    for a in [0, 1, 2, 4, 3, 5, 7, 6, 8]:
        e.play(a)
    assert e.terminal()
    assert e.outcome() == {0: 0, 1: 0}


def test_geister_piece_accounting():
    random.seed(3)
    e = _make("Geister")
    for _ in range(20):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
            counts = e._piece_counts()
            total = sum(counts[0]) + sum(counts[1])
            assert total == int(e.alive.sum()) <= 16
        assert e.win_color in (0, 1, 2)


def test_hungry_geese_ranking():
    e = _make("HungryGeese")
    e.reset()
    e.rank_rewards = [400, 400, 300, 100]
    out = e.outcome()
    assert out[0] == out[1] > out[2] > out[3]
    assert abs(sum(out.values())) < 1e-9


def test_observation_viewpoint_rotation():
    """Geister: White's observation is the 180-rotation of the board."""
    random.seed(4)
    e = _make("Geister")
    e.reset()
    e.play(144)  # black layout 0
    e.play(144)  # white layout 0
    obs_b = e.observation(0)
    obs_w = e.observation(1)
    assert obs_b["board"].shape == (7, 6, 6)
    assert obs_w["board"].shape == (7, 6, 6)
    # plane 1 is "my pieces": white's own pieces rotated must equal black's view of white pieces
    np.testing.assert_allclose(
        np.rot90(obs_w["board"][1], k=2, axes=(0, 1)), obs_b["board"][2]
    )
