"""Device-resident replay (runtime/device_replay.py) parity tests.

The bar: a window sampled and assembled ON DEVICE must equal, key by key,
the batch the host path (StreamingDeviceRollout episode assembly ->
EpisodeStore window -> make_batch) builds for the SAME episode, window
start, and target player.  Both paths consume the identical streaming-fn
records, so every difference is an assembly bug, not sampling noise.
"""

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.envs.vector_hungry_geese import VectorHungryGeese
from handyrl_tpu.models import init_variables
from handyrl_tpu.parallel import TrainContext, make_mesh
from handyrl_tpu.runtime.batch import make_batch
from handyrl_tpu.runtime.device_replay import DeviceReplay
from handyrl_tpu.runtime.device_rollout import _streaming_episode, build_streaming_fn
from handyrl_tpu.utils import tree_map

N_LANES = 8
K_STEPS = 32
N_CALLS = 10          # 320 steps > SLOTS: the ring wraps and invalidation runs
SLOTS = 192


def _args(env_name: str = "HungryGeese", **overrides):
    train = {
        "turn_based_training": False,
        "observation": False,
        "batch_size": 8,
        "forward_steps": 8,
        "burn_in_steps": 0,
    }
    train.update(overrides)
    cfg = normalize_args(
        {"env_args": {"env": env_name}, "train_args": train}
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    return args


def _drive_rollout(env_name: str, venv, n_lanes: int, k_steps: int,
                   n_calls: int, slots: int, **arg_overrides):
    """Drive the streaming fn once; return the host episodes (with their
    [lane, g0, g1] global-step spans) and a DeviceReplay holding the SAME
    records — the two sides every parity check compares."""
    env = make_env({"env": env_name})
    module = env.net()
    params = init_variables(module, env)["params"]
    args = _args(env_name, **arg_overrides)

    mesh = make_mesh({"dp": 1})
    fn = build_streaming_fn(venv, module, n_lanes, k_steps, mesh=None,
                            use_observe_mask=bool(args["observation"]))
    replay = DeviceReplay(venv, module, args, mesh, n_lanes, slots=slots)

    state = venv.init(n_lanes, jax.random.PRNGKey(7))
    hidden = module.initial_state((n_lanes, venv.num_players))
    key = jax.random.PRNGKey(42)
    chunks = []
    for _ in range(n_calls):
        key, sub = jax.random.split(key)
        state, hidden, records = fn(params, state, hidden, sub)
        records = jax.device_get(records)
        chunks.append(records)
        replay.ingest(tree_map(np.asarray, records))

    full = tree_map(lambda *xs: np.concatenate(xs), *chunks)  # (G, B, ...)
    G = n_calls * k_steps

    episodes = []                     # (lane, g0, g1, host episode dict)
    done = full["done"]               # (G, B)
    for b in range(n_lanes):
        g0 = 0
        for g1 in np.flatnonzero(done[:, b]):
            g1 = int(g1)
            ep = _streaming_episode(venv, [(full, g0, g1 + 1)], full, g1, b, args)
            episodes.append((b, g0, g1, ep))
            g0 = g1 + 1
    assert len(episodes) >= 10, "rollout produced too few finished episodes"
    return {
        "episodes": episodes, "replay": replay, "module": module,
        "params": params, "args": args, "G": G, "mesh": mesh,
        "n_lanes": n_lanes, "slots": slots,
    }


@pytest.fixture(scope="module")
def rollout_data():
    return _drive_rollout("HungryGeese", VectorHungryGeese,
                          N_LANES, K_STEPS, N_CALLS, SLOTS)


def _host_window(ep, train_start, args):
    """Reconstruct the exact sample_window dict (replay.py:110-140) for a
    forced train_start."""
    fwd, cs = args["forward_steps"], args["compress_steps"]
    steps = ep["steps"]
    start = max(0, train_start - args["burn_in_steps"])
    end = min(train_start + fwd, steps)
    first_block = start // cs
    last_block = (end - 1) // cs + 1
    return {
        "args": ep["args"],
        "outcome": np.asarray([ep["outcome"][p] for p in ep["players"]], np.float32),
        "players": ep["players"],
        "blocks": ep["blocks"][first_block:last_block],
        "base": first_block * cs,
        "start": start,
        "end": end,
        "train_start": train_start,
        "total": steps,
    }


def _check_windows(data, monkeypatch, n: int, seed: int = 3):
    """Key-by-key equality of device-assembled windows vs make_batch on the
    same (episode, train_start, target player)."""
    replay, args = data["replay"], data["args"]
    episodes = data["episodes"]
    G, S = data["G"], data["slots"]

    batch, info = replay.sample(jax.random.PRNGKey(seed), n, with_info=True)
    batch = tree_map(np.asarray, batch)

    for i in range(n):
        lane, slot, player = int(info["lane"][i]), int(info["slot"][i]), int(info["player"][i])
        gs0 = G - 1 - ((G - 1 - slot) % S)    # global step held by the slot
        hits = [e for e in episodes if e[0] == lane and e[1] <= gs0 <= e[2]]
        assert hits, f"sampled slot maps to no finished episode (lane {lane}, g {gs0})"
        b, g0, g1, ep = hits[0]
        # the device only samples eligible starts: finished episode, within
        # the host sampler's train_start range
        train_start = gs0 - g0
        assert train_start <= max(0, ep["steps"] - args["forward_steps"])

        if player >= 0:  # ff mode samples one target player per window
            monkeypatch.setattr(
                "handyrl_tpu.runtime.batch.random.randrange", lambda _n: player
            )
        host = make_batch([_host_window(ep, train_start, args)], args)

        for key in host:
            if key == "observation":  # pytree for some envs (Geister)
                for hl, dl in zip(jax.tree.leaves(host[key]), jax.tree.leaves(batch[key])):
                    np.testing.assert_allclose(
                        dl[i : i + 1], hl, atol=1e-6, err_msg=f"{key} row {i}"
                    )
            else:
                np.testing.assert_allclose(
                    batch[key][i : i + 1], host[key], atol=1e-6, err_msg=f"{key} row {i}"
                )


def test_sampled_windows_match_make_batch(rollout_data, monkeypatch):
    _check_windows(rollout_data, monkeypatch, n=48)


def test_parallel_tictactoe_device_replay_parity(monkeypatch):
    """The second device-replay env: VectorParallelTicTacToe windows must
    match make_batch the same way (9-step episodes, heavy auto-reset —
    many episodes per ring cycle, the opposite regime from geese)."""
    from handyrl_tpu.envs.vector_parallel_tictactoe import VectorParallelTicTacToe

    data = _drive_rollout("ParallelTicTacToe", VectorParallelTicTacToe,
                          n_lanes=4, k_steps=12, n_calls=6, slots=32)
    _check_windows(data, monkeypatch, n=32)


@pytest.fixture(scope="module")
def geister_rollout_data():
    """The turn-based + recurrent mode: VectorGeister with the DRC net,
    observation: true (both players' views + observer omask), burn-in 4."""
    from handyrl_tpu.envs.vector_geister import VectorGeister

    # random Geister games mostly reach the 200-ply draw, so each lane
    # needs ~700 steps to finish >=3 episodes
    return _drive_rollout(
        "Geister", VectorGeister, n_lanes=4, k_steps=32, n_calls=22,
        slots=256, turn_based_training=True, observation=True,
        burn_in_steps=4,
    )


@pytest.mark.slow  # ~3 min of jitted DRC rollout on the CPU mesh
def test_geister_turn_windows_match_make_batch(geister_rollout_data, monkeypatch):
    """Turn-mode device windows (all players, burn-in rows, DRC records)
    must equal make_batch key by key on the same episode + train_start."""
    _check_windows(geister_rollout_data, monkeypatch, n=32)


@pytest.mark.slow
def test_geister_turn_train_fn_runs(geister_rollout_data):
    """Recurrent sample+SGD straight from the rings: the train step's RNN
    scan consumes the device-assembled (B, T, P, ...) window (burn-in under
    stop_gradient) — finite loss, params move."""
    data = geister_rollout_data
    ctx = TrainContext(data["module"], data["args"], data["mesh"])
    state = ctx.init_state(data["params"])
    before = jax.device_get(state["params"])
    fn = data["replay"].train_fn(ctx, fused_steps=1)
    state, metrics = fn(state, jax.random.PRNGKey(11), 1e-3)
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"]) and m["dcnt"] > 0
    after = jax.device_get(state["params"])
    assert max(
        float(np.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(after), jax.tree.leaves(before))
    ) > 0, "params did not move"


def test_transformer_turn_mode_trains_from_rings():
    """The transformer family (KV-cache hidden, seq-attention training)
    through turn-mode device replay: streamed Geister records ingest into
    rings, windows assemble on device, and the seq-path train step
    consumes them — finite loss, real data count.  Completes the
    model-family x data-path matrix (DRC was the only turn-mode net)."""
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.envs.vector_geister import VectorGeister
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.device_rollout import build_streaming_fn

    env = make_env({
        "env": "Geister", "net": "transformer",
        "net_args": {"d_model": 32, "n_heads": 2, "n_layers": 2,
                     "memory_len": 8},
    })
    module = env.net()
    params = init_variables(module, env)["params"]
    cfg = normalize_args({
        "env_args": {"env": "Geister"},
        "train_args": {"turn_based_training": True, "observation": True,
                       "batch_size": 4, "forward_steps": 4,
                       "burn_in_steps": 2, "seq_attention": "einsum",
                       "mesh": {"dp": 1}},
    })
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    mesh = make_mesh({"dp": 1})
    lanes = 4
    fn = build_streaming_fn(VectorGeister, module, lanes, 64, mesh=None,
                            use_observe_mask=True)
    replay = DeviceReplay(VectorGeister, module, args, mesh, lanes, slots=64)
    state = VectorGeister.init(lanes, jax.random.PRNGKey(3))
    hidden = module.initial_state((lanes, VectorGeister.num_players))
    key = jax.random.PRNGKey(4)
    for _ in range(5):
        key, sub = jax.random.split(key)
        state, hidden, records = fn(params, state, hidden, sub)
        replay.ingest(records)
    assert replay.eligible_count() > 0
    ctx = TrainContext(module, args, mesh)
    train = replay.train_fn(ctx, fused_steps=1)
    tstate, metrics = train(ctx.init_state(params), jax.random.PRNGKey(5), 1e-4)
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"]) and m["dcnt"] > 0


def test_eligibility_and_wrap(rollout_data):
    """After the ring wraps, every eligible slot belongs to a finished,
    still-resident episode — and partially-overwritten episodes only offer
    window starts whose full window is resident."""
    from handyrl_tpu.runtime.device_replay import _eligibility

    replay = rollout_data["replay"]
    episodes = rollout_data["episodes"]
    args = rollout_data["args"]
    G, S = rollout_data["G"], SLOTS
    assert G > S, "test must exercise ring wrap"

    ok = np.asarray(_eligibility(replay.rings, args["forward_steps"]))
    assert ok.any(), "no eligible slots after ingest"
    spans = {}
    for b, g0, g1, ep in episodes:
        spans.setdefault(b, []).append((g0, g1))
    for b in range(N_LANES):
        for s in np.flatnonzero(ok[b]):
            gs = G - 1 - ((G - 1 - int(s)) % S)
            in_ep = [sp for sp in spans.get(b, []) if sp[0] <= gs <= sp[1]]
            assert in_ep, f"eligible slot outside any finished episode (lane {b})"
            g0, g1 = in_ep[0]
            # episode end must still be resident (windows read forward)
            assert g1 > G - 1 - S


def test_train_fn_runs_and_updates(rollout_data):
    """Fused sample+SGD from the rings: finite loss, params actually move,
    metrics summed over fused steps (dcnt ~ fused * batch turn sum)."""
    replay = rollout_data["replay"]
    module, params, args = (
        rollout_data["module"], rollout_data["params"], rollout_data["args"],
    )
    ctx = TrainContext(module, args, rollout_data["mesh"])
    state = ctx.init_state(params)
    before = jax.device_get(state["params"])
    fn = replay.train_fn(ctx, fused_steps=2)
    state, metrics = fn(state, jax.random.PRNGKey(5), 1e-3)
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"]) and m["dcnt"] > 0
    after = jax.device_get(state["params"])
    diffs = [
        float(np.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(after), jax.tree.leaves(before))
    ]
    assert max(diffs) > 0, "params did not move"
    assert int(jax.device_get(state["steps"])) == 2


def test_learner_device_replay_end_to_end(tmp_path, monkeypatch):
    """Full --train stack with device_replay: the data path never builds a
    host episode, yet epochs advance, generation stats are booked from
    ingest counters, checkpoints land, and metrics.jsonl records updates."""
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 8,
            "forward_steps": 8,
            "minimum_episodes": 10,
            # the epoch cadence is episode-counted (reference semantics):
            # size the budget so the run outlasts the one-off CPU compile
            # of the fused sample+train step, else it ends with 0 updates
            "update_episodes": 40,
            "maximum_episodes": 1000,
            "epochs": 2,
            "eval_rate": 0.0,
            "device_rollout_games": 8,
            "device_replay": True,
            "device_replay_slots": 256,
            "device_replay_k_steps": 16,
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(cfg)
    learner.run()

    records = [json.loads(l) for l in open("metrics.jsonl")]
    # `epochs` counts MODEL UPDATES; a metrics record is written at every
    # epoch boundary, including pre-warmup ones where the trainer had
    # nothing yet — on a loaded host that adds an extra leading record
    # (reproduced 2026-08-01 under a concurrent suite run)
    assert 2 <= len(records) <= 3
    assert records[-1]["steps"] > 0, "no SGD updates ran"
    assert records[-1]["episodes"] >= 80, "episode counters did not reach epoch 2"
    # generation stats came from device counters (host saw no episodes)
    assert any("generation_mean" in r for r in records)
    # per-epoch self-play volume -> survival signal in the metrics
    assert any(r.get("device_mean_episode_len", 0) > 1 for r in records)
    assert os.path.exists("models/latest.ckpt")
    assert os.path.exists("models/state.ckpt")
    assert learner.trainer.store.total_added == 0, (
        "device_replay must not materialize host episodes"
    )


@pytest.mark.slow
def test_learner_geister_device_replay_end_to_end(tmp_path, monkeypatch):
    """Full --train stack on the turn-based + recurrent mode: Geister DRC
    trained from device rings (burn-in windows, all-player batches), no
    host episodes materialized, epochs advance, checkpoints land."""
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "Geister"},
        "train_args": {
            "turn_based_training": True,
            "observation": True,
            "batch_size": 4,
            "forward_steps": 4,
            "burn_in_steps": 2,
            "minimum_episodes": 2,
            "update_episodes": 2,
            "maximum_episodes": 100,
            "epochs": 1,
            "eval_rate": 0.0,
            "device_rollout_games": 2,
            "device_replay": True,
            "device_replay_slots": 256,
            "device_replay_k_steps": 64,
            "mesh": {"dp": 1},
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(cfg)
    learner.run()

    records = [json.loads(l) for l in open("metrics.jsonl")]
    # epochs count model updates; pre-warmup boundaries may add a leading
    # record on a loaded host (see the geese test above)
    assert 1 <= len(records) <= 2
    assert records[-1]["steps"] > 0, "no SGD updates ran"
    assert os.path.exists("models/latest.ckpt")
    assert learner.trainer.store.total_added == 0, (
        "device_replay must not materialize host episodes"
    )


def test_ingest_counted_deferred_matches_sync(rollout_data):
    """The direct-ingest hot path (learner rollout thread): deferred stats
    fetching (ingest_counted defer=True + flush_counted) must land the
    same cumulative counters as the synchronous per-dispatch fetch — the
    deferral only moves WHEN the scalar fetch happens, never what it
    counts."""
    env = make_env({"env": "HungryGeese"})
    module = env.net()
    params = init_variables(module, env)["params"]
    args = rollout_data["args"]
    mesh = rollout_data["mesh"]
    fn = build_streaming_fn(VectorHungryGeese, module, 4, 16, mesh=None,
                            use_observe_mask=False)
    sync = DeviceReplay(VectorHungryGeese, module, args, mesh, 4, slots=64)
    deferred = DeviceReplay(VectorHungryGeese, module, args, mesh, 4, slots=64)
    state = VectorHungryGeese.init(4, jax.random.PRNGKey(21))
    key = jax.random.PRNGKey(22)
    chunks = []
    for _ in range(5):
        key, sub = jax.random.split(key)
        state, _, records = fn(params, state, None, sub)
        chunks.append(tree_map(np.asarray, jax.device_get(records)))
    returned_eps = 0
    for rec in chunks:
        sync.ingest_counted(rec)
        out = deferred.ingest_counted(rec, defer=True)
        if out is not None:
            returned_eps += int(out["episodes"])
    # mid-stream the deferred side lags exactly one dispatch
    assert deferred.counters["episodes"] <= sync.counters["episodes"]
    tail = deferred.flush_counted()
    assert tail is not None
    returned_eps += int(tail["episodes"])
    assert deferred.counters == sync.counters
    # every episode was also RETURNED to the caller exactly once
    assert returned_eps == sync.counters["episodes"]


def test_ingest_stats_match_records(rollout_data):
    """Ingest counters must agree with host-side counting of the same
    records (episodes finished, game/player steps)."""
    env = make_env({"env": "HungryGeese"})
    module = env.net()
    params = init_variables(module, env)["params"]
    args = rollout_data["args"]
    mesh = rollout_data["mesh"]
    fn = build_streaming_fn(VectorHungryGeese, module, 4, 16, mesh=None,
                            use_observe_mask=False)
    replay = DeviceReplay(VectorHungryGeese, module, args, mesh, 4, slots=64)
    state = VectorHungryGeese.init(4, jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(2)
    tot = {"episodes": 0, "game_steps": 0, "player_steps": 0}
    for _ in range(6):
        key, sub = jax.random.split(key)
        state, _, records = fn(params, state, None, sub)
        records = tree_map(np.asarray, jax.device_get(records))
        stats = tree_map(np.asarray, replay.ingest(records))
        assert stats["episodes"] == records["done"].sum()
        assert stats["game_steps"] == (records["active"].sum(axis=2) > 0).sum()
        assert stats["player_steps"] == records["active"].sum()
        for k in tot:
            tot[k] += int(stats[k])
    assert tot["episodes"] > 0 and tot["game_steps"] >= tot["episodes"]
    assert replay.eligible_count() > 0
