"""Model family tests: shapes, recurrence, determinism, batching."""

import jax

import numpy as np
import pytest

from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, RandomModel, init_variables


def _build(name):
    env = make_env({"env": name})
    module = env.net()
    variables = init_variables(module, env, seed=0)
    return env, module, InferenceModel(module, variables)


@pytest.mark.parametrize("name,num_actions", [("TicTacToe", 9), ("HungryGeese", 4)])
def test_feedforward_nets(name, num_actions):
    env, module, model = _build(name)
    env.reset()
    obs = env.observation(env.players()[0])
    out = model.inference(obs, model.init_hidden())
    assert out["policy"].shape == (num_actions,)
    assert out["value"].shape == (1,)
    assert -1 <= float(out["value"][0]) <= 1
    # batched path agrees with single path
    obs_b = jax.tree.map(lambda x: np.stack([x, x]), obs)
    out_b = model.inference_batch(obs_b)
    np.testing.assert_allclose(out_b["policy"][0], out_b["policy"][1], atol=1e-5)
    np.testing.assert_allclose(out_b["policy"][0], out["policy"], atol=1e-5)


def test_geister_recurrent_net():
    env, module, model = _build("Geister")
    env.reset()
    env.play(144)
    env.play(150)
    obs = env.observation(0)
    hidden = model.init_hidden()
    assert hidden is not None
    out = model.inference(obs, hidden)
    assert out["policy"].shape == (214,)
    assert out["value"].shape == (1,)
    assert out["return"].shape == (1,)
    # hidden state evolves and feeds back
    h1 = out["hidden"]
    assert not np.allclose(h1[0], hidden[0])
    out2 = model.inference(obs, h1)
    assert not np.allclose(out2["value"], out["value"]) or not np.allclose(
        out2["hidden"][0], h1[0]
    )


def test_geister_hidden_batch_leading():
    env, module, model = _build("Geister")
    hidden = model.init_hidden((5, 2))
    assert hidden[0].shape == (5, 2, 3, 6, 6, 32)


def test_random_model():
    env, module, model = _build("TicTacToe")
    env.reset()
    obs = env.observation(0)
    rm = RandomModel.from_model(model, obs)
    out = rm.inference(obs)
    assert np.all(out["policy"] == 0)
    assert np.all(out["value"] == 0)


def test_jit_cache_no_recompile():
    """Repeated same-shape inference hits the jit cache (one compile)."""
    env, module, model = _build("TicTacToe")
    env.reset()
    obs = env.observation(0)
    model.inference(obs)
    compiled_before = model._apply._cache_size()
    for _ in range(5):
        model.inference(obs)
    assert model._apply._cache_size() == compiled_before
