"""Transformer (KV-cache memory) model family tests: step semantics,
memory behavior, engine/export compatibility, and the full training path
through the recurrent lax.scan hidden-carry machinery.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, TransformerNet, init_variables


def _model(env_args):
    env = make_env(env_args)
    module = env.net()
    variables = init_variables(module, env)
    return env, module, InferenceModel(module, variables)


def test_transformer_step_and_memory():
    env, module, model = _model({"env": "TicTacToe", "net": "transformer"})
    assert isinstance(module, TransformerNet)
    env.reset()
    obs = env.observation(0)

    hidden = model.init_hidden()
    assert float(hidden["pos"]) == 0.0
    out1 = model.inference(obs, hidden)
    assert out1["policy"].shape == (9,)
    assert -1.0 <= float(out1["value"][0]) <= 1.0
    h1 = out1["hidden"]
    assert float(h1["pos"]) == 1.0
    # a cache slot was written
    assert np.abs(np.asarray(h1["layers"][0]["k"])).sum() > 0

    # memory matters: the same query with a DIFFERENT history step differs
    # (history must contain distinct content, else all cached values match)
    env.play(4)
    obs2 = env.observation(0)
    out_fresh = model.inference(obs2, model.init_hidden())
    out_mem = model.inference(obs2, h1)  # h1 remembers the empty board
    assert not np.allclose(out_fresh["policy"], out_mem["policy"], atol=1e-4)


def test_transformer_net_args_override():
    """env_args['net_args'] scales the family without a new env subclass
    (the bench's MXU-saturation stage and scale configs rely on this)."""
    env, module, model = _model({
        "env": "Geister", "net": "transformer",
        "net_args": {"d_model": 32, "n_heads": 2, "n_layers": 3,
                     "memory_len": 8},
    })
    assert isinstance(module, TransformerNet)
    assert (module.d_model, module.n_heads, module.n_layers,
            module.memory_len) == (32, 2, 3, 8)
    assert module.with_return  # env's spec survives the merge
    env.reset()
    out = model.inference(env.observation(0), model.init_hidden())
    assert out["policy"].shape == (env.action_size(),)
    assert len(out["hidden"]["layers"]) == 3


def test_stateful_model_without_observation_fails_fast():
    """A recurrent/memory model with observation: false must be rejected
    at TrainContext construction (clear startup error), not crash a
    learner thread mid-training on batch shapes (found by driving
    main.py --train with a transformer config missing the flag)."""
    from handyrl_tpu.parallel import TrainContext, make_mesh

    cfg = normalize_args(
        {
            "env_args": {"env": "TicTacToe", "net": "transformer"},
            "train_args": {"batch_size": 8, "forward_steps": 4},
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    assert not args.get("observation")
    env = make_env(args["env"])
    with pytest.raises(ValueError, match="observation: true"):
        TrainContext(env.net(), args, make_mesh(args["mesh"]))


def test_bench_tpu_transformer_config_traces():
    """Abstractly evaluate the EXACT train program the bench's TPU-gated
    transformer stage compiles on-chip (whatever shape
    bench.TRANSFORMER_TPU_NET_ARGS currently pins — d1536/L8/H16, B64,
    T64, bf16, einsum attention as of the 2026-08-02 width sweep; the
    flash path's kernel shapes are covered by the battery in
    tests/test_flash_attention.py).  The stage never executes in CI, so without
    this trace a shape bug in the big config would first surface
    mid-capture on a live chip lease.  eval_shape runs the full trace —
    forward, attention, losses, grads, Adam — without lowering or
    allocating the big-net state."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import bench
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch
    from handyrl_tpu.models import RandomModel
    from handyrl_tpu.utils import tree_map

    cfg = normalize_args(
        {
            "env_args": {"env": "Geister", "net": "transformer",
                         "net_args": bench.TRANSFORMER_TPU_NET_ARGS},
            "train_args": dict(bench.TRANSFORMER_TPU_OVERRIDES),
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    env = make_env(args["env"])
    module = env.net()
    # derived from the bench pin, not hard-coded: the whole point of this
    # guard is to trace whatever the chip-gated stage will actually
    # compile, so a re-pinned width must never desynchronize it again
    assert (module.d_model, module.n_layers) == (
        bench.TRANSFORMER_TPU_NET_ARGS["d_model"],
        bench.TRANSFORMER_TPU_NET_ARGS["n_layers"],
    )

    # abstract params/opt state: no 134M-param allocation
    env.reset()
    obs_b = tree_map(lambda x: jnp.asarray(np.asarray(x))[None], env.observation(0))
    var_shape = jax.eval_shape(
        module.init, jax.random.PRNGKey(0), obs_b, module.initial_state((1,))
    )
    mesh = make_mesh({"dp": -1})
    ctx = TrainContext(module, args, mesh)
    state_shape = jax.eval_shape(
        lambda p: {"params": p, "opt_state": ctx.tx.init(p),
                   "steps": jnp.zeros((), jnp.int32)},
        var_shape["params"],
    )

    # a real batch at the exact stage geometry (windows resampled from a
    # couple of random games — shapes are what matter here); the
    # RandomModel spec is written out directly so nothing compiles or
    # allocates the big net on the CPU test backend
    small = make_env(args["env"])
    small.reset()
    A = small.action_size()
    rm = RandomModel({"policy": ((A,), np.float32),
                      "value": ((1,), np.float32),
                      "return": ((1,), np.float32)})
    store = EpisodeStore(64)
    gen = Generator(small, args)
    gen_args = {"player": small.players(), "model_id": {p: 0 for p in small.players()}}
    while len(store) < 2:
        ep = gen.generate({p: rm for p in small.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], args["burn_in_steps"],
                                args["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)
    assert batch["action"].shape[:3] == (64, 64, 2)

    new_state, metrics = jax.eval_shape(
        ctx._step_fn, state_shape, batch,
        jax.ShapeDtypeStruct((), jnp.float32),
    )
    # donation compatibility: the updated state must mirror the input layout
    assert jax.tree.structure(new_state) == jax.tree.structure(state_shape)
    chex = [(a.shape, a.dtype) for a in jax.tree.leaves(new_state)]
    want = [(a.shape, a.dtype) for a in jax.tree.leaves(state_shape)]
    assert chex == want
    assert set(metrics) >= {"p", "v", "ent", "total", "dcnt"}


def test_bench_transformer_long_t1024_pin_traces():
    """Abstractly evaluate the LONGEST-T program the transformer_long
    bench stage will compile on-chip: T1024 x d1536 x L8, flash kernel
    auto-picked (T >= flash_min_t), remat 'block' (what 'auto' resolves to
    on TPU at this T), bf16 compute.  Same contract as
    test_bench_tpu_transformer_config_traces: the stage's big points are
    chip-gated, so this trace is what keeps a shape bug from first
    surfacing mid-capture on a live lease."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import bench
    from handyrl_tpu.models import RandomModel
    from handyrl_tpu.parallel import TrainContext, make_mesh, resolve_seq_attention
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch
    from handyrl_tpu.utils import tree_map

    pins = bench.TRANSFORMER_LONG_TPU
    T = pins["sweep_t"][-1]
    B = pins["batch_by_t"][T]
    cfg = normalize_args(
        {
            "env_args": {"env": "Geister", "net": "transformer",
                         "net_args": pins["net_args"]},
            "train_args": {
                "batch_size": B, "burn_in_steps": 0, "forward_steps": T,
                "observation": True, "seq_attention": "auto",
                "flash_min_t": pins["flash_min_t"],
                "compute_dtype": pins["compute_dtype"],
                "remat": "block",
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    assert resolve_seq_attention(args, T) == "flash"

    env = make_env(args["env"])
    module = env.net()
    assert module.d_model == pins["net_args"]["d_model"]
    env.reset()
    obs_b = tree_map(lambda x: jnp.asarray(np.asarray(x))[None], env.observation(0))
    var_shape = jax.eval_shape(
        module.init, jax.random.PRNGKey(0), obs_b, module.initial_state((1,))
    )
    ctx = TrainContext(module, args, make_mesh({"dp": -1}))
    state_shape = jax.eval_shape(
        lambda p: {"params": p, "opt_state": ctx.tx.init(p),
                   "steps": jnp.zeros((), jnp.int32)},
        var_shape["params"],
    )

    small = make_env(args["env"])
    small.reset()
    A = small.action_size()
    rm = RandomModel({"policy": ((A,), np.float32),
                      "value": ((1,), np.float32),
                      "return": ((1,), np.float32)})
    store = EpisodeStore(16)
    gen = Generator(small, args)
    gen_args = {"player": small.players(), "model_id": {p: 0 for p in small.players()}}
    while len(store) < 2:
        ep = gen.generate({p: rm for p in small.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], args["burn_in_steps"],
                                args["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)
    assert batch["action"].shape[:3] == (B, T, 2)

    new_state, metrics = jax.eval_shape(
        ctx._step_fn, state_shape, batch,
        jax.ShapeDtypeStruct((), jnp.float32),
    )
    got = [(a.shape, a.dtype) for a in jax.tree.leaves(new_state)]
    want = [(a.shape, a.dtype) for a in jax.tree.leaves(state_shape)]
    assert got == want
    assert set(metrics) >= {"p", "v", "ent", "total", "dcnt"}


def test_transformer_ring_wraparound():
    env, module, model = _model({"env": "TicTacToe", "net": "transformer"})
    env.reset()
    obs = env.observation(0)
    hidden = model.init_hidden()
    for _ in range(module.memory_len + 5):  # past the ring size
        out = model.inference(obs, hidden)
        hidden = out["hidden"]
    assert float(hidden["pos"]) == module.memory_len + 5
    assert np.isfinite(np.asarray(out["policy"])).all()


def test_transformer_through_inference_engine():
    from handyrl_tpu.runtime import BatchedInferenceEngine

    env, module, model = _model({"env": "TicTacToe", "net": "transformer"})
    env.reset()
    obs = env.observation(0)
    engine = BatchedInferenceEngine(model, max_batch=4).start()
    client = engine.client()
    direct = model.inference(obs, model.init_hidden())
    via_engine = client.inference(obs, None)  # None -> initial state slice
    engine.stop()
    np.testing.assert_allclose(via_engine["policy"], direct["policy"], rtol=2e-4, atol=2e-5)


def test_transformer_export_roundtrip(tmp_path):
    from handyrl_tpu.models import ExportedModel, export_model

    env, module, model = _model({"env": "TicTacToe", "net": "transformer"})
    env.reset()
    obs = env.observation(0)
    path = str(tmp_path / "ttt_tf.hlo")
    export_model(module, model.variables, obs, path)
    ex = ExportedModel(path)
    o1 = model.inference(obs, model.init_hidden())
    o2 = ex.inference(obs, ex.init_hidden())
    np.testing.assert_allclose(o1["policy"], o2["policy"], rtol=1e-4, atol=1e-5)


def _transformer_batch(env_name, burn_in=2, forward_steps=4, batch_size=8,
                       net_args=None, train_over=None):
    from handyrl_tpu.models import RandomModel
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    env_args = {"env": env_name, "net": "transformer"}
    if net_args:
        env_args["net_args"] = net_args
    cfg = normalize_args(
        {
            "env_args": env_args,
            "train_args": {
                "batch_size": batch_size,
                "forward_steps": forward_steps,
                "burn_in_steps": burn_in,
                "compress_steps": 4,
                "observation": True,
                **(train_over or {}),
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))
    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 6:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], args["burn_in_steps"], args["compress_steps"])
        if w is not None:
            windows.append(w)
    return env, module, variables, make_batch(windows, args), args


def test_transformer_seq_path_matches_scan():
    """The whole-window attention path must equal the KV-cache scan path —
    in values AND in parameter gradients (burn-in stop_gradient included)."""
    from handyrl_tpu.parallel import forward_prediction

    env, module, variables, batch, args = _transformer_batch("TicTacToe")
    batch = jax.tree.map(jax.numpy.asarray, batch)
    out_seq = forward_prediction(module, variables["params"], batch, {**args, "seq_forward": True})
    out_scan = forward_prediction(module, variables["params"], batch, {**args, "seq_forward": False})
    assert set(out_seq) == set(out_scan)
    for k in out_seq:
        np.testing.assert_allclose(
            np.asarray(out_seq[k]), np.asarray(out_scan[k]), rtol=2e-4, atol=2e-4
        )

    def loss(params, seq_forward):
        # realistic downstream use: softmax over action-masked logits (the
        # raw logits carry -1e32 mask values; squaring those is numeric noise)
        outs = forward_prediction(module, params, batch, {**args, "seq_forward": seq_forward})
        p = jax.nn.softmax(outs["policy"], axis=-1)
        rest = sum((v ** 2).sum() for k, v in outs.items() if k != "policy")
        return (p ** 2).sum() + rest

    g_seq = jax.grad(lambda p: loss(p, True))(variables["params"])
    g_scan = jax.grad(lambda p: loss(p, False))(variables["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4
        ),
        g_seq,
        g_scan,
    )


def test_resolve_seq_attention_policy():
    """The auto-pick policy, in one place: einsum below flash_min_t, the
    Pallas kernel at and above it, explicit modes pass through."""
    from handyrl_tpu.parallel import resolve_seq_attention, resolve_seq_remat

    args = {"seq_attention": "auto", "flash_min_t": 128}
    assert resolve_seq_attention(args, 64) == "einsum"
    assert resolve_seq_attention(args, 127) == "einsum"
    assert resolve_seq_attention(args, 128) == "flash"
    assert resolve_seq_attention(args, 1024) == "flash"
    for mode in ("einsum", "flash", "ring"):
        assert resolve_seq_attention({"seq_attention": mode}, 8) == mode
    # remat rungs: ladder strings pass through, booleans collapse, auto
    # is 'none' off-TPU (this suite runs on CPU)
    assert resolve_seq_remat({"remat": "attn"}, 1024) == "attn"
    assert resolve_seq_remat({"remat": True}, 8) == "block"
    assert resolve_seq_remat({"remat": False}, 4096) == "none"
    assert resolve_seq_remat({"remat": "auto"}, 4096) == "none"
    # ring attention never composes with the ladder: the ring partitions
    # activation memory itself, and checkpoint-around-shard_map fails
    assert resolve_seq_remat(
        {"remat": "auto", "seq_attention": "ring"}, 4096
    ) == "none"


def test_seq_remat_bit_parity():
    """The remat ladder must not change the math at a T64 window: the
    jitted LOSS is bit-identical across remat none/attn/block, and
    parameter gradients agree to float-reassociation precision (the
    checkpoint's optimization barriers change XLA's fusion of the
    backward, so reductions reassociate at the ~1e-9 level — same ops,
    same inputs, different summation order; anything larger would be a
    real semantics change)."""
    from handyrl_tpu.parallel import forward_prediction

    env, module, variables, batch, args = _transformer_batch(
        "TicTacToe", burn_in=2, forward_steps=62,
        # small width keeps the three T64 jit compiles cheap; the ladder's
        # structure (per-block checkpoints, qkv tags) is width-independent
        net_args={"d_model": 32, "n_heads": 2, "n_layers": 2, "memory_len": 16},
    )
    batch = jax.tree.map(jax.numpy.asarray, batch)

    def loss(params, remat):
        outs = forward_prediction(
            module, params, batch, {**args, "seq_forward": True, "remat": remat}
        )
        p = jax.nn.softmax(outs["policy"], axis=-1)
        rest = sum((v ** 2).sum() for k, v in outs.items() if k != "policy")
        return (p ** 2).sum() + rest

    # none vs block is the acceptance pair (the 'attn' rung sits between
    # them structurally and rides the slow-leg memory test); two T64
    # compiles keep this inside the tier-1 budget
    vg = {
        remat: jax.jit(jax.value_and_grad(lambda p, r=remat: loss(p, r)))(
            variables["params"]
        )
        for remat in ("none", "block")
    }
    base_l, base_g = vg["none"]
    for remat in ("block",):
        l, g = vg[remat]
        # bit-identical on this container's jaxlib; the rtol guard keeps a
        # future XLA that fuses the checkpointed forward differently from
        # turning a last-ulp reassociation into a spurious CI failure
        np.testing.assert_allclose(
            np.asarray(l), np.asarray(base_l), rtol=1e-7, err_msg=remat
        )
        for a, b in zip(jax.tree.leaves(base_g), jax.tree.leaves(g)):
            # atol floor: near-zero bias grads are pure cancellation noise
            # (magnitudes ~1e-8), where reassociation moves them ~1e-7
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6, err_msg=remat
            )


@pytest.mark.slow
def test_seq_remat_reduces_peak_memory():
    """The point of the ladder: at a long window the checkpointed blocks
    compile to a measurably smaller peak (XLA compiled memory analysis —
    temp bytes) than remat 'none'.  T1024 x 4 layers of einsum attention
    keeps 4 (B, H, T, T) score/softmax slabs live without remat; 'block'
    keeps block inputs + the tagged q/k/v only.  Slow leg: three T1024
    XLA:CPU compiles (~90 s on a 2-core host)."""
    module = TransformerNet(
        num_actions=4, d_model=64, n_heads=2, n_layers=4, memory_len=64
    )
    B, T = 1, 1024
    obs = jnp.zeros((B, T, 8), jnp.float32)
    km = jnp.ones((B, T), jnp.float32)
    params = module.init(
        jax.random.PRNGKey(0), obs, None, seq=True, key_mask=km
    )["params"]

    def temp_bytes(remat):
        def loss(p):
            out = module.apply(
                {"params": p}, obs, None, seq=True, key_mask=km, remat=remat
            )
            return (out["policy"] ** 2).sum() + (out["value"] ** 2).sum()

        lowered = jax.jit(jax.grad(loss)).lower(params)
        return int(lowered.compile().memory_analysis().temp_size_in_bytes)

    none_b, attn_b, block_b = (temp_bytes(r) for r in ("none", "attn", "block"))
    # each rung must buy real memory: 'attn' strictly below 'none', and
    # 'block' at least 25% below (XLA keeps the transient forward slabs
    # either way, so the saving here is the per-layer residual set — the
    # margin grows with n_layers on the production 8-layer pin)
    assert attn_b < none_b, (none_b, attn_b)
    assert block_b < 0.75 * none_b, (none_b, block_b)


@pytest.mark.slow
def test_long_context_train_step_t1024_d1536():
    """The acceptance shape: a T1024 x d1536 train step compiles AND steps
    under the remat ladder on the CPU mesh, with the remat-none peak
    measured (never executed — that is the OOM-by-construction program at
    production batch sizes) strictly above the ladder's."""
    from handyrl_tpu.parallel import TrainContext, make_mesh

    env, module, variables, batch, args = _transformer_batch(
        "TicTacToe", burn_in=0, forward_steps=1024, batch_size=2,
        net_args={"d_model": 1536, "n_heads": 16, "n_layers": 2,
                  "memory_len": 64},
        train_over={"seq_attention": "einsum", "remat": "block",
                    "mesh": {"dp": 1}},
    )
    mesh = make_mesh({"dp": 1})
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(variables["params"])
    device_batch = ctx.put_batch(batch)

    def peak(ctx_, state_, batch_):
        lowered = ctx_._bind(state_).lower(
            state_, batch_, jax.ShapeDtypeStruct((), jnp.float32)
        )
        return int(lowered.compile().memory_analysis().temp_size_in_bytes)

    ctx_none = TrainContext(module, dict(args, remat="none"), mesh)
    state_shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
    batch_shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), device_batch
    )
    peak_block = peak(ctx, state_shapes, batch_shapes)
    peak_none = peak(ctx_none, state_shapes, batch_shapes)
    assert peak_block < peak_none, (peak_block, peak_none)

    state, metrics = ctx.train_step(state, device_batch, 1e-4)
    assert np.isfinite(float(jax.device_get(metrics["total"])))


@pytest.mark.parametrize("env_name", ["TicTacToe", "Geister"])
def test_transformer_train_step(env_name):
    """Full sharded train step through the scan/burn-in recurrent path."""
    from handyrl_tpu.models import RandomModel
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    cfg = normalize_args(
        {
            "env_args": {"env": env_name, "net": "transformer"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "burn_in_steps": 2,
                "compress_steps": 4,
                "observation": True,  # recurrent path needs full-player batches
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 6:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], args["burn_in_steps"], args["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)

    ctx = TrainContext(module, args, make_mesh({"dp": -1}))
    state = ctx.init_state(variables["params"])
    state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
    assert np.isfinite(float(jax.device_get(metrics["total"])))


def test_transformer_train_step_tensor_parallel():
    """The transformer's Dense kernels under an 'mp' mesh axis: the same
    batch + params on a dp x mp mesh must produce the same update metrics
    as the dp-only run (GSPMD inserts the tp gathers; shape-based kernel
    sharding from parallel/mesh.py applies to the attention/MLP Dense
    layers exactly as to conv kernels)."""
    from handyrl_tpu.models import RandomModel
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    cfg = normalize_args(
        {
            "env_args": {"env": "TicTacToe", "net": "transformer"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "burn_in_steps": 2,
                "compress_steps": 4,
                "observation": True,
                "seq_attention": "einsum",
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 6:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(
            args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
        )
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)

    metrics_by_mesh = {}
    for name, mesh_spec in [("dp", {"dp": 4}), ("dpmp", {"dp": 4, "mp": 2})]:
        ctx = TrainContext(module, args, make_mesh(mesh_spec))
        state = ctx.init_state(variables["params"])
        _, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
        metrics_by_mesh[name] = {
            k: float(jax.device_get(v)) for k, v in metrics.items()
        }
    assert np.isfinite(metrics_by_mesh["dpmp"]["total"])
    for k in ("total", "p", "v", "dcnt"):
        np.testing.assert_allclose(
            metrics_by_mesh["dpmp"][k], metrics_by_mesh["dp"][k],
            rtol=1e-4, atol=1e-6, err_msg=k,
        )


def test_transformer_train_step_ring_sp():
    """seq_attention='ring': the FULL train step on a dp x sp mesh with the
    transformer window sharded across the 'sp' axis — metrics must match
    the einsum path on the same mesh AND the single-chip einsum step (the
    dp x sp composition changes the program layout, not the semantics).
    Real pass on this container's jax 0.4.37 via the _ring_loop compat
    ladder (identity marking on pre-VMA jax)."""
    from handyrl_tpu.models import RandomModel
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime import EpisodeStore, Generator, make_batch

    cfg = normalize_args(
        {
            "env_args": {"env": "TicTacToe", "net": "transformer"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 8,  # T = 8, divisible by sp = 4
                "burn_in_steps": 0,
                "compress_steps": 4,
                "observation": True,
                "seq_forward": True,
                "mesh": {"dp": 2, "sp": 4},
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    module = env.net()
    variables = init_variables(module, env)
    model = InferenceModel(module, variables)
    env.reset()
    random_model = RandomModel.from_model(model, env.observation(env.players()[0]))

    store = EpisodeStore(64)
    gen = Generator(env, args)
    gen_args = {"player": env.players(), "model_id": {p: 0 for p in env.players()}}
    while len(store) < 6:
        ep = gen.generate({p: random_model for p in env.players()}, gen_args)
        if ep is not None:
            store.extend([ep])
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], args["burn_in_steps"], args["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)

    mesh = make_mesh(args["mesh"])
    results = {}
    for mode in ("einsum", "ring"):
        ctx = TrainContext(module, {**args, "seq_attention": mode}, mesh)
        state = ctx.init_state(variables["params"])
        state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
        results[mode] = jax.device_get(metrics)
    # the single-chip einsum step: same params/batch, no mesh axes at all
    ctx1 = TrainContext(
        module, {**args, "seq_attention": "einsum", "mesh": {"dp": 1}},
        make_mesh({"dp": 1}),
    )
    state = ctx1.init_state(variables["params"])
    _, metrics = ctx1.train_step(state, ctx1.put_batch(batch), 1e-4)
    results["single_chip"] = jax.device_get(metrics)
    for k in ("total", "p", "v", "dcnt"):
        np.testing.assert_allclose(
            results["ring"][k], results["einsum"][k], rtol=2e-4, atol=2e-5
        )
        # bf16-tolerance bound vs the single chip (the acceptance bar);
        # everything here runs fp32 so the observed gap is far tighter
        np.testing.assert_allclose(
            results["ring"][k], results["single_chip"][k], rtol=8e-3, atol=1e-4
        )


def test_ring_mode_requires_sp_axis():
    """seq_attention='ring' without an 'sp' mesh axis fails loudly at
    CONFIG time (normalize_args), and the same guard still fires at
    TrainContext construction for direct-API callers who skip the config
    layer — never deep inside the first traced step."""
    from handyrl_tpu.parallel import TrainContext, make_mesh

    with pytest.raises(ValueError, match="sp"):
        normalize_args(
            {
                "env_args": {"env": "TicTacToe", "net": "transformer"},
                "train_args": {"seq_attention": "ring", "batch_size": 8},
            }
        )
    cfg = normalize_args(
        {"env_args": {"env": "TicTacToe", "net": "transformer"},
         "train_args": {"batch_size": 8}}
    )
    args = dict(cfg["train_args"], seq_attention="ring", observation=True)
    args["env"] = cfg["env_args"]
    env = make_env(args["env"])
    with pytest.raises(ValueError, match="sp"):
        TrainContext(env.net(), args, make_mesh({"dp": -1}))


def test_ring_mode_requires_divisible_window():
    from handyrl_tpu.parallel import TrainContext, make_mesh

    raw_train = {
        "seq_attention": "ring", "batch_size": 8,
        "forward_steps": 10, "mesh": {"dp": 2, "sp": 4},
    }
    with pytest.raises(ValueError, match="divisible"):
        normalize_args(
            {"env_args": {"env": "TicTacToe", "net": "transformer"},
             "train_args": raw_train}
        )
    cfg = normalize_args(
        {"env_args": {"env": "TicTacToe", "net": "transformer"},
         "train_args": {**raw_train, "forward_steps": 12}}
    )
    args = dict(cfg["train_args"], forward_steps=10, observation=True)
    args["env"] = cfg["env_args"]
    env = make_env(args["env"])
    with pytest.raises(ValueError, match="divisible"):
        TrainContext(env.net(), args, make_mesh(args["mesh"]))


def test_attn_mode_alias_and_knob_validation():
    """attn_mode aliases seq_attention; blk/remat/mesh knobs are validated
    loudly at config time (the PR 6 fail-at-startup pattern)."""
    cfg = normalize_args(
        {"env_args": {"env": "TicTacToe", "net": "transformer"},
         "train_args": {"attn_mode": "flash"}}
    )
    assert cfg["train_args"]["seq_attention"] == "flash"
    assert "attn_mode" not in cfg["train_args"]
    base = {"env_args": {"env": "TicTacToe"}}
    with pytest.raises(ValueError, match="alias"):
        normalize_args(
            {**base, "train_args": {"attn_mode": "flash", "seq_attention": "einsum"}}
        )
    with pytest.raises(ValueError, match="blk_q"):
        normalize_args({**base, "train_args": {"blk_q": 96}})
    with pytest.raises(ValueError, match="power of two"):
        normalize_args({**base, "train_args": {"blk_k": 4}})
    with pytest.raises(ValueError, match="remat"):
        normalize_args({**base, "train_args": {"remat": "everything"}})
    # bare ints are rejected: 1 == True under tuple membership, but the
    # isinstance-based resolver would read it as 'auto' — refuse the
    # ambiguity at config time
    with pytest.raises(ValueError, match="remat"):
        normalize_args({**base, "train_args": {"remat": 1}})
    with pytest.raises(ValueError, match="mesh"):
        normalize_args({**base, "train_args": {"mesh": {"dp": -1, "sp": -1}}})
    with pytest.raises(ValueError, match="mesh"):
        normalize_args({**base, "train_args": {"mesh": {"dp": 0}}})
    # booleans and ladder strings are all legal remat spellings
    for v in ("auto", True, False, "none", "attn", "block"):
        normalize_args({**base, "train_args": {"remat": v}})
    # ring + a forced remat rung is a rejected composition (checkpoint
    # around the shard_map ring loop fails its scan-carry typing)
    with pytest.raises(ValueError, match="ring"):
        normalize_args(
            {**base, "train_args": {
                "seq_attention": "ring", "remat": "block",
                "forward_steps": 16, "mesh": {"dp": 2, "sp": 4},
            }}
        )
