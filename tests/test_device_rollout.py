"""On-device self-play (runtime/device_rollout.py) parity tests.

The device path must produce episodes that are (a) legal games under the
canonical host rules, (b) in the exact columnar schema the replay/batch
pipeline consumes, and (c) trainable end-to-end.
"""

import jax
import numpy as np

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.envs.vector_tictactoe import VectorTicTacToe
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.runtime.device_rollout import DeviceRollout
from handyrl_tpu.runtime.replay import EpisodeStore, decompress_block


def _setup(n_games=64):
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    variables = init_variables(module, env)
    cfg = normalize_args(
        {"env_args": {"env": "TicTacToe"}, "train_args": {"batch_size": 8, "forward_steps": 8}}
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    roll = DeviceRollout(VectorTicTacToe, module, args, n_games=n_games)
    episodes = roll.generate(variables["params"], jax.random.PRNGKey(0))
    return env, module, variables, args, episodes


def test_device_games_replay_legally_on_host():
    """Every device-generated game must be a legal host-env game with the
    same outcome — the rules-parity bar for the jnp transition functions."""
    env, module, variables, args, episodes = _setup()
    assert len(episodes) == 64
    for ep in episodes:
        cols = [decompress_block(b) for b in ep["blocks"]]
        actions = np.concatenate([c["action"] for c in cols])   # (T, P)
        tmask = np.concatenate([c["tmask"] for c in cols])
        turn = np.concatenate([c["turn"] for c in cols])
        env.reset()
        for t in range(ep["steps"]):
            p = int(turn[t])
            assert p == env.turn()
            assert tmask[t, p] == 1.0 and tmask[t, 1 - p] == 0.0
            a = int(actions[t, p])
            assert a in env.legal_actions(p), (t, a)
            env.play(a, p)
        assert env.terminal()
        assert env.outcome() == ep["outcome"]


def test_device_columns_match_host_model():
    """Recorded obs/prob/value must be what the live model would produce
    for the replayed position (same params, same masking math)."""
    env, module, variables, args, episodes = _setup(n_games=8)
    model = InferenceModel(module, variables)
    ep = episodes[0]
    cols = [decompress_block(b) for b in ep["blocks"]]
    obs = np.concatenate([c["obs"] for c in cols])
    prob = np.concatenate([c["prob"] for c in cols])
    value = np.concatenate([c["value"] for c in cols])
    action = np.concatenate([c["action"] for c in cols])
    amask = np.concatenate([c["amask"] for c in cols])
    turn = np.concatenate([c["turn"] for c in cols])

    env.reset()
    from handyrl_tpu.utils import softmax

    for t in range(ep["steps"]):
        p = int(turn[t])
        np.testing.assert_allclose(obs[t, p], env.observation(p), atol=1e-6)
        out = model.inference(env.observation(p))
        np.testing.assert_allclose(value[t, p], out["value"][0], rtol=2e-4, atol=2e-5)
        legal = env.legal_actions(p)
        expected_mask = np.full(9, 1e32, np.float32)
        expected_mask[legal] = 0.0
        np.testing.assert_array_equal(amask[t, p], expected_mask)
        probs = softmax(np.asarray(out["policy"], np.float32) - expected_mask)
        np.testing.assert_allclose(prob[t, p], probs[int(action[t, p])], rtol=2e-3, atol=1e-4)
        env.play(int(action[t, p]), p)


def test_device_episodes_train():
    """Device episodes flow through the standard store -> make_batch ->
    sharded train step."""
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime.batch import make_batch

    env, module, variables, args, episodes = _setup()
    store = EpisodeStore(256)
    store.extend(episodes)
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], 0, args["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)
    ctx = TrainContext(module, args, make_mesh({"dp": -1}))
    state = ctx.init_state(variables["params"])
    state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"]) and m["dcnt"] > 0


def test_learner_with_device_rollouts(tmp_path, monkeypatch):
    """Full learner stack with on-device generation: device batches feed
    the store and drive the epoch cadence; host workers keep evaluating."""
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "batch_size": 8,
            "forward_steps": 4,
            "minimum_episodes": 40,
            "update_episodes": 40,
            "maximum_episodes": 400,
            "epochs": 2,
            "num_batchers": 1,
            "eval_rate": 0.2,
            "device_rollout_games": 32,
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(args)
    learner.run()

    assert os.path.exists("models/2.ckpt")
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) >= 2
    assert learner.num_returned_episodes >= 80
