"""On-device self-play (runtime/device_rollout.py) parity tests.

The device path must produce episodes that are (a) legal games under the
canonical host rules, (b) in the exact columnar schema the replay/batch
pipeline consumes, and (c) trainable end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.envs.vector_tictactoe import VectorTicTacToe
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.runtime.device_rollout import DeviceRollout
from handyrl_tpu.runtime.replay import EpisodeStore, decompress_block


def _setup(n_games=64):
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    variables = init_variables(module, env)
    cfg = normalize_args(
        {"env_args": {"env": "TicTacToe"}, "train_args": {"batch_size": 8, "forward_steps": 8}}
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    roll = DeviceRollout(VectorTicTacToe, module, args, n_games=n_games)
    episodes = roll.generate(variables["params"], jax.random.PRNGKey(0))
    return env, module, variables, args, episodes


def test_device_games_replay_legally_on_host():
    """Every device-generated game must be a legal host-env game with the
    same outcome — the rules-parity bar for the jnp transition functions."""
    env, module, variables, args, episodes = _setup()
    assert len(episodes) == 64
    for ep in episodes:
        cols = [decompress_block(b) for b in ep["blocks"]]
        actions = np.concatenate([c["action"] for c in cols])   # (T, P)
        tmask = np.concatenate([c["tmask"] for c in cols])
        turn = np.concatenate([c["turn"] for c in cols])
        env.reset()
        for t in range(ep["steps"]):
            p = int(turn[t])
            assert p == env.turn()
            assert tmask[t, p] == 1.0 and tmask[t, 1 - p] == 0.0
            a = int(actions[t, p])
            assert a in env.legal_actions(p), (t, a)
            env.play(a, p)
        assert env.terminal()
        assert env.outcome() == ep["outcome"]


def test_device_columns_match_host_model():
    """Recorded obs/prob/value must be what the live model would produce
    for the replayed position (same params, same masking math)."""
    env, module, variables, args, episodes = _setup(n_games=8)
    model = InferenceModel(module, variables)
    ep = episodes[0]
    cols = [decompress_block(b) for b in ep["blocks"]]
    obs = np.concatenate([c["obs"] for c in cols])
    prob = np.concatenate([c["prob"] for c in cols])
    value = np.concatenate([c["value"] for c in cols])
    action = np.concatenate([c["action"] for c in cols])
    amask = np.concatenate([c["amask"] for c in cols])
    turn = np.concatenate([c["turn"] for c in cols])

    env.reset()
    from handyrl_tpu.utils import softmax

    for t in range(ep["steps"]):
        p = int(turn[t])
        np.testing.assert_allclose(obs[t, p], env.observation(p), atol=1e-6)
        out = model.inference(env.observation(p))
        np.testing.assert_allclose(value[t, p], out["value"][0], rtol=2e-4, atol=2e-5)
        legal = env.legal_actions(p)
        expected_mask = np.full(9, 1e32, np.float32)
        expected_mask[legal] = 0.0
        np.testing.assert_array_equal(amask[t, p], expected_mask)
        probs = softmax(np.asarray(out["policy"], np.float32) - expected_mask)
        np.testing.assert_allclose(prob[t, p], probs[int(action[t, p])], rtol=2e-3, atol=1e-4)
        env.play(int(action[t, p]), p)


def test_device_episodes_train():
    """Device episodes flow through the standard store -> make_batch ->
    sharded train step."""
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime.batch import make_batch

    env, module, variables, args, episodes = _setup()
    store = EpisodeStore(256)
    store.extend(episodes)
    windows = []
    while len(windows) < args["batch_size"]:
        w = store.sample_window(args["forward_steps"], 0, args["compress_steps"])
        if w is not None:
            windows.append(w)
    batch = make_batch(windows, args)
    ctx = TrainContext(module, args, make_mesh({"dp": -1}))
    state = ctx.init_state(variables["params"])
    state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
    m = jax.device_get(metrics)
    assert np.isfinite(m["total"]) and m["dcnt"] > 0


def test_custom_env_device_twin_replays_legally():
    """The custom-env example's device twin (examples.connect_four
    ConnectFourRules lifted by envs/autovec.py — the worked 'write your
    game once' twin-less example, no hand-written vector env) must clear
    the same rules-parity bar as the bundled hand twins: every
    device-generated game replays legally through the host rules with the
    identical outcome, and the recorded observations match the host
    views."""
    from examples.connect_four import Environment

    env = Environment()
    module = env.net()
    variables = init_variables(module, env)
    cfg = normalize_args(
        {
            "env_args": {"env": "examples.connect_four"},
            "train_args": {"batch_size": 8, "forward_steps": 8},
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    venv = Environment.vector_env()
    assert getattr(venv, "__autovec__", False), "example twin must be autovec-lifted"
    roll = DeviceRollout(venv, module, args, n_games=32)
    episodes = roll.generate(variables["params"], jax.random.PRNGKey(7))
    assert len(episodes) == 32
    saw_win = False
    for ep in episodes:
        cols = [decompress_block(b) for b in ep["blocks"]]
        actions = np.concatenate([c["action"] for c in cols])   # (T, P)
        obs = np.concatenate([c["obs"] for c in cols])
        turn = np.concatenate([c["turn"] for c in cols])
        env.reset()
        for t in range(ep["steps"]):
            p = int(turn[t])
            assert p == env.turn()
            a = int(actions[t, p])
            assert a in env.legal_actions(p), (t, a)
            np.testing.assert_allclose(obs[t, p], env.observation(p), atol=1e-6)
            env.play(a, p)
        assert env.terminal()
        assert env.outcome() == ep["outcome"]
        saw_win |= ep["outcome"][0] != 0.0
    assert saw_win  # random 6x7 games essentially always produce wins


class TestVectorGeeseParity:
    """VectorHungryGeese (envs/vector_hungry_geese.py) vs the canonical
    host rules, lock-step: every phase of the transition — reversal /
    self-collision / starvation deaths, food growth, hunger, cross-goose
    collisions, rank credit, episode end — must match the host env for the
    same actions, with the device's food spawns injected into the host
    (host food placement is `random.choice`; positions are the only
    nondeterminism, and uniformity is asserted separately)."""

    def _init_pair(self, n_lanes, seed):
        from handyrl_tpu.envs.hungry_geese import Environment
        from handyrl_tpu.envs.vector_hungry_geese import VectorHungryGeese as V

        state = V.init(n_lanes, jax.random.PRNGKey(seed))
        hosts = []
        for b in range(n_lanes):
            e = Environment()
            e.reset()
            e.geese = [[V.body_list(state, b, p)[0]] for p in range(4)]
            e.food = [int(c) for c in np.flatnonzero(np.asarray(state["food"])[b])]
            hosts.append(e)
        return V, state, hosts

    def _assert_lane(self, V, state, host, b, ctx):
        for p in range(4):
            assert V.body_list(state, b, p) == list(host.geese[p]), (ctx, b, p)
            assert bool(np.asarray(state["active"])[b, p]) == host.active[p], (ctx, b, p)
            assert int(np.asarray(state["rank"])[b, p]) == host.rank_rewards[p], (ctx, b, p)
        assert bool(np.asarray(state["done"])[b]) == host.terminal(), (ctx, b)

    def _run_lockstep(self, n_lanes, n_steps, seed, policy):
        V, state, hosts = self._init_pair(n_lanes, seed)
        step = jax.jit(V.step)
        rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(seed + 1)
        finished = 0
        max_step_seen = 0
        for t in range(n_steps):
            actions = policy(hosts, rng)
            key, ks = jax.random.split(key)
            prev_done = np.asarray(state["done"]).copy()
            prev_food = [set(host.food) for host in hosts]  # common pre-step food
            state = step(state, jnp.asarray(actions), ks)
            for b, host in enumerate(hosts):
                if prev_done[b]:
                    continue
                host.step({p: int(actions[b, p]) for p in host.turns()})
                dev_food = set(
                    int(c) for c in np.flatnonzero(np.asarray(state["food"])[b])
                )
                # Food parity BEFORE injecting the device's spawns: both
                # sides must keep/remove the same pre-existing food (eating
                # semantics) and reach the same count (spawn-to-MIN_FOOD
                # semantics); only spawn POSITIONS may differ (RNG).
                assert dev_food & prev_food[b] == set(host.food) & prev_food[b], (t, b)
                assert len(dev_food) == len(host.food), (t, b)
                host.food = list(dev_food)
                max_step_seen = max(max_step_seen, host.step_count)
                if host.terminal():
                    finished += 1
                self._assert_lane(V, state, hosts[b], b, t)
        return finished, max_step_seen

    def test_lockstep_random(self):
        """Random actions: exercises reversal deaths, head-on collisions,
        food growth, early episode ends."""
        finished, _ = self._run_lockstep(
            48, 40, 0, lambda hosts, rng: rng.integers(0, 4, (len(hosts), 4)).astype(np.int32)
        )
        assert finished >= 40  # random geese die fast; most games must finish

    def test_lockstep_greedy_reaches_hunger(self):
        """Greedy survival policy: games must live past step 40 so the
        hunger tail-pop (t % 40 == 0) and long-body dynamics are covered."""
        import random as _random

        _random.seed(7)  # rule_based_action falls back to random.choice

        def policy(hosts, rng):
            acts = np.zeros((len(hosts), 4), np.int32)
            for b, host in enumerate(hosts):
                for p in range(4):
                    acts[b, p] = (
                        host.rule_based_action(p) if host.active[p]
                        else rng.integers(0, 4)
                    )
            return acts

        finished, max_step = self._run_lockstep(12, 70, 7, policy)
        assert max_step > 40, "no game survived past the hunger step"

    def test_contested_food_goes_to_lowest_index(self):
        """Host food consumption is sequential: when two geese reach the
        same food, only the lower-indexed one eats; the loser pops its
        tail, which a THIRD goose colliding with that tail cell observes
        (it survives iff the tail was popped).  Regression for the
        parallel-eat shortcut that kept the loser's tail."""
        from handyrl_tpu.envs.vector_hungry_geese import (
            MAXLEN, VectorHungryGeese as V,
        )

        # board cells r*11+c: food F=38 at (3,5); goose 0 head 37 moves E;
        # goose 1 body [39, 40] moves W (loses the food race, pops 40);
        # goose 2 head 29 moves S onto 40 (survives iff 40 was popped);
        # goose 3 far away at 66 moves N.
        cells = np.zeros((1, 4, MAXLEN), np.int32)
        cells[0, 0, 0] = 37
        cells[0, 1, 0], cells[0, 1, 1] = 39, 40
        cells[0, 2, 0] = 29
        cells[0, 3, 0] = 66
        occ = np.zeros((1, 4, 77), np.int8)
        for p, body in enumerate([[37], [39, 40], [29], [66]]):
            occ[0, p, body] = 1
        food = np.zeros((1, 77), np.int8)
        food[0, [38, 76]] = 1
        state = {
            "cells": jnp.asarray(cells),
            "head_ptr": jnp.zeros((1, 4), jnp.int32),
            "length": jnp.asarray([[1, 2, 1, 1]], jnp.int32),
            "occ": jnp.asarray(occ),
            "active": jnp.ones((1, 4), bool),
            "last_action": jnp.full((1, 4), -1, jnp.int32),
            "prev_head": jnp.full((1, 4), -1, jnp.int32),
            "rank": jnp.full((1, 4), 101, jnp.int32),
            "food": jnp.asarray(food),
            "step": jnp.zeros((1,), jnp.int32),
            "done": jnp.zeros((1,), bool),
        }
        actions = jnp.asarray([[3, 2, 1, 0]], jnp.int32)  # E, W, S, N
        out = V.step(state, actions, jax.random.PRNGKey(0))
        active = np.asarray(out["active"])[0]
        # geese 0 and 1 share head cell 38 and both die; goose 2 must
        # SURVIVE because goose 1 did not eat and popped its tail at 40
        assert list(active) == [False, False, True, True]
        assert V.body_list(out, 0, 2) == [40]
        # the contested food is consumed exactly once
        assert np.asarray(out["food"])[0, 38] == 0

    def test_food_spawn_uniform_and_valid(self):
        """Device food spawns land only on free cells and cover the board
        roughly uniformly (the host uses random.choice over free cells)."""
        from handyrl_tpu.envs.vector_hungry_geese import VectorHungryGeese as V

        state = V.init(256, jax.random.PRNGKey(3))
        step = jax.jit(V.step)
        key = jax.random.PRNGKey(4)
        rng = np.random.default_rng(5)
        counts = np.zeros(77, np.int64)
        for t in range(12):
            key, ks = jax.random.split(key)
            prev_food = np.asarray(state["food"]).copy()
            state = V.reset_done(state, jax.random.fold_in(key, t))
            state = step(state, jnp.asarray(rng.integers(0, 4, (256, 4)), np.int32), ks)
            food, occ = np.asarray(state["food"]), np.asarray(state["occ"]).sum(1)
            assert not np.any((food > 0) & (occ > 0)), "food spawned on a goose"
            new = (food > 0) & (prev_food == 0)
            counts += new.sum(0)
        assert counts.sum() > 500
        # uniformity: no cell should dominate (loose 5x-of-mean bound)
        assert counts.max() < 5 * counts.mean() + 10


class TestStreamingRollout:
    """StreamingDeviceRollout: persistent lanes, auto-reset, episode
    stitching across calls, columnar schema, trainability."""

    def _episodes(self, n_calls=6, n_lanes=32, k_steps=16, seed=0, mesh=None):
        from handyrl_tpu.envs.vector_hungry_geese import VectorHungryGeese
        from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

        env = make_env({"env": "HungryGeese"})
        module = env.net()
        variables = init_variables(module, env)
        cfg = normalize_args({
            "env_args": {"env": "HungryGeese"},
            "train_args": {"batch_size": 8, "forward_steps": 8,
                           "turn_based_training": False, "observation": False},
        })
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        roll = StreamingDeviceRollout(
            VectorHungryGeese, module, args, n_lanes=n_lanes, k_steps=k_steps,
            mesh=mesh,
        )
        key = jax.random.PRNGKey(seed)
        episodes = []
        for _ in range(n_calls):
            key, sub = jax.random.split(key)
            episodes += roll.generate(variables["params"], sub)
        return env, module, variables, args, roll, episodes

    def test_schema_and_outcomes(self):
        env, module, variables, args, roll, episodes = self._episodes()
        assert len(episodes) > 10
        assert roll.game_steps > 0 and roll.player_steps >= roll.game_steps
        for ep in episodes:
            cols = [decompress_block(b) for b in ep["blocks"]]
            obs = np.concatenate([c["obs"] for c in cols])
            tmask = np.concatenate([c["tmask"] for c in cols])
            amask = np.concatenate([c["amask"] for c in cols])
            assert obs.shape[1:] == (4, 17, 7, 11)
            assert amask.shape[1:] == (4, 4)  # full action dim (mixes with host episodes)
            assert sum(c["prob"].shape[0] for c in cols) == ep["steps"]
            # zero-sum pairwise rank outcome (fp32 on device: 1/3 rounds)
            assert abs(sum(ep["outcome"].values())) < 1e-6
            # all four geese act at step one; actors strictly shrink
            n_act = tmask.sum(axis=1)
            assert n_act[0] == 4.0
            assert (np.diff(n_act) <= 0 + 1e-9).all()
            # active rows carry an all-legal mask, dead rows the 1e32 fill
            assert ((amask == 0.0) == (tmask[..., None] > 0)).all()

    def test_observations_match_host_builder(self):
        """Rebuilt compact-record observations must equal the host env's
        observation() for the same reconstructed position."""
        from handyrl_tpu.envs.hungry_geese import Environment

        # one block is always in flight (compute/assembly overlap), so
        # n_calls=4 assembles 3 blocks = 48 steps — past the t=40 die-off
        env, module, variables, args, roll, episodes = self._episodes(n_calls=4)
        checked = 0
        for ep in episodes[:8]:
            cols = [decompress_block(b) for b in ep["blocks"]]
            obs = np.concatenate([c["obs"] for c in cols])
            tmask = np.concatenate([c["tmask"] for c in cols])
            # reconstruct host state at t=0 from the obs planes themselves:
            # single-cell geese + food — then verify the builder agrees
            host = Environment()
            host.reset()
            heads = [int(np.flatnonzero(obs[0, 0, 8 + ((p - 0) % 4)].reshape(-1))[0])
                     for p in range(4)]
            host.geese = [[heads[p]] for p in range(4)]
            host.food = [int(c) for c in np.flatnonzero(obs[0, 0, 16].reshape(-1))]
            host.prev_heads = [None] * 4
            for p in range(4):
                if tmask[0, p] > 0:
                    np.testing.assert_array_equal(obs[0, p], host.observation(p))
                    checked += 1
        assert checked >= 8

    def test_streaming_episodes_train(self):
        from handyrl_tpu.parallel import TrainContext, make_mesh
        from handyrl_tpu.runtime.batch import make_batch

        env, module, variables, args, roll, episodes = self._episodes()
        store = EpisodeStore(512)
        store.extend(episodes)
        windows = []
        while len(windows) < args["batch_size"]:
            w = store.sample_window(args["forward_steps"], 0, args["compress_steps"])
            if w is not None:
                windows.append(w)
        batch = make_batch(windows, args)
        ctx = TrainContext(module, args, make_mesh({"dp": -1}))
        state = ctx.init_state(variables["params"])
        state, metrics = ctx.train_step(state, ctx.put_batch(batch), 1e-4)
        m = jax.device_get(metrics)
        assert np.isfinite(m["total"]) and m["dcnt"] > 0

    def test_sharded_lanes_over_mesh(self):
        """Streaming rollout as one SPMD program: lanes sharded over the
        8-device CPU mesh's 'dp' axis, params replicated — the actor-plane
        analogue of the data-parallel train step."""
        from handyrl_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": -1})
        env, module, variables, args, roll, episodes = self._episodes(
            n_calls=7, n_lanes=16, k_steps=8, mesh=mesh
        )
        assert episodes, "sharded rollout produced no episodes"
        for ep in episodes[:4]:
            cols = [decompress_block(b) for b in ep["blocks"]]
            obs = np.concatenate([c["obs"] for c in cols])
            assert obs.shape[1:] == (4, 17, 7, 11)
            # float32 rank-ladder outcomes: a two-way tie (-2/3 twice) sums
            # to ~3e-8, not 0.0 — the zero-sum bound must be fp32-scale
            assert abs(sum(ep["outcome"].values())) < 1e-6

    def test_lanes_stitch_across_calls(self):
        """Episodes longer than k_steps must span device calls.  The
        freshly-initialized GeeseNet is near-deterministic (large logit
        scale), so whole populations march in lockstep until the t=40
        hunger pop starves them — 12 calls x 4 steps crosses that point,
        and every such episode spans ~10 device calls."""
        env, module, variables, args, roll, episodes = self._episodes(
            n_calls=12, n_lanes=16, k_steps=4
        )
        assert episodes, "no episode finished in 48 steps"
        assert max(ep["steps"] for ep in episodes) > 4


class TestVectorGeisterParity:
    """VectorGeister vs the canonical host rules, lock-step: placement,
    frame-rotated move decoding, captures + win conditions, 200-ply draw,
    legal masks, and per-player observations must all match."""

    def test_lockstep_random_legal(self):
        from handyrl_tpu.envs.geister import Environment
        from handyrl_tpu.envs.vector_geister import VectorGeister as V

        B = 6
        key = jax.random.PRNGKey(3)
        state = V.init(B, key)
        step = jax.jit(V.step)
        legal_fn = jax.jit(V.legal_mask_all)
        obs_fn = jax.jit(V.observation)
        hosts = [Environment() for _ in range(B)]
        for h in hosts:
            h.reset()

        finished = 0
        for t in range(120):
            lm = np.asarray(legal_fn(state))             # (B, P, 214)
            obs = jax.device_get(obs_fn(state)) if t % 7 == 0 else None
            prev_done = np.asarray(state["done"]).copy()
            ply = np.asarray(state["ply"])
            acts = np.zeros((B, 2), np.int32)
            for b, h in enumerate(hosts):
                if prev_done[b]:
                    continue
                c = ply[b] % 2
                assert ply[b] == h.ply and c == h.turn(), (t, b)
                # legal-mask parity with the host
                dev_legal = set(np.flatnonzero(lm[b, c]).tolist())
                assert dev_legal == set(h.legal_actions()), (t, b, ply[b])
                # observation parity for both players (every 7th ply)
                for p in range(2) if obs is not None else ():
                    host_obs = h.observation(p)
                    np.testing.assert_allclose(
                        obs["scalar"][b, p], host_obs["scalar"], atol=1e-6
                    )
                    np.testing.assert_allclose(
                        obs["board"][b, p], host_obs["board"], atol=1e-6
                    )
                acts[b, c] = np.random.RandomState(1000 * t + b).choice(
                    sorted(dev_legal)
                )
            key, ks = jax.random.split(key)
            state = step(state, jnp.asarray(acts), ks)
            for b, h in enumerate(hosts):
                if prev_done[b]:
                    continue
                c = ply[b] % 2
                h.play(int(acts[b, c]))
                # full state parity after the ply
                assert (np.asarray(state["board"])[b].reshape(6, 6)
                        == h.board).all(), (t, b)
                win = int(np.asarray(state["win"])[b])
                host_win = -1 if h.win_color is None else h.win_color
                assert win == host_win, (t, b, win, host_win)
                assert bool(np.asarray(state["done"])[b]) == h.terminal()
                if h.terminal():
                    finished += 1
        assert finished >= 1  # random games regularly end within 80 plies

    def test_streaming_episodes_and_training(self):
        """Streaming rollout with the recurrent DRC net: episodes appear
        (the near-deterministic init net shuffle-loops to the 200-ply
        draw), carry the turn-alternating masks and pytree observations,
        and train through the RNN burn-in path."""
        from handyrl_tpu.envs.vector_geister import VectorGeister
        from handyrl_tpu.parallel import TrainContext, make_mesh
        from handyrl_tpu.runtime.batch import make_batch
        from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

        env = make_env({"env": "Geister"})
        module = env.net()
        variables = init_variables(module, env)
        cfg = normalize_args({
            "env_args": {"env": "Geister"},
            "train_args": {"batch_size": 8, "forward_steps": 8,
                           "burn_in_steps": 4, "observation": True},
        })
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        roll = StreamingDeviceRollout(
            VectorGeister, module, args, n_lanes=8, k_steps=32
        )
        key = jax.random.PRNGKey(0)
        episodes = []
        for _ in range(8):
            key, sub = jax.random.split(key)
            episodes += roll.generate(variables["params"], sub)
        assert episodes, "no Geister episode finished in 224 plies"

        ep = episodes[0]
        cols = [decompress_block(b) for b in ep["blocks"]]
        scalar = np.concatenate([c["obs"]["scalar"] for c in cols])
        board = np.concatenate([c["obs"]["board"] for c in cols])
        tmask = np.concatenate([c["tmask"] for c in cols])
        omask = np.concatenate([c["omask"] for c in cols])
        amask = np.concatenate([c["amask"] for c in cols])
        reward = np.concatenate([c["reward"] for c in cols])
        T = ep["steps"]
        assert scalar.shape == (T, 2, 18) and board.shape == (T, 2, 7, 6, 6)
        # strict alternation: exactly one actor per step, Black first
        assert (tmask.sum(axis=1) == 1.0).all()
        assert (tmask[:, 0] == (np.arange(T) % 2 == 0)).all()
        # both players observe every step (DRC hidden advances for both)
        assert (omask == 1.0).all()
        # placement plies offer exactly the 70 layouts
        assert (amask[0, 0] == 0).sum() == 70 and (amask[1, 1] == 0).sum() == 70
        # per-step reward for both players (host reward(), geister.py:253-254)
        np.testing.assert_allclose(reward, -0.01 * np.ones((T, 2)), atol=1e-7)
        assert ep["outcome"][0] == -ep["outcome"][1]

        store = EpisodeStore(64)
        store.extend(episodes)
        windows = []
        while len(windows) < args["batch_size"]:
            w = store.sample_window(
                args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
            )
            if w is not None:
                windows.append(w)
        batch = make_batch(windows, args)
        ctx = TrainContext(module, args, make_mesh({"dp": -1}))
        tstate = ctx.init_state(variables["params"])
        tstate, metrics = ctx.train_step(tstate, ctx.put_batch(batch), 1e-4)
        m = jax.device_get(metrics)
        assert np.isfinite(m["total"]) and m["dcnt"] > 0

    def test_streaming_transformer_kv_cache_hidden(self):
        """The transformer family's KV-cache hidden must ride the SAME
        streaming hidden-carry machinery as the DRC ConvLSTM: lanes carry
        per-(lane, player) cache pytrees, episodes finish, and the
        harvested windows train through the seq-attention path."""
        from handyrl_tpu.envs.vector_geister import VectorGeister
        from handyrl_tpu.parallel import TrainContext, make_mesh
        from handyrl_tpu.runtime.batch import make_batch
        from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

        env = make_env({
            "env": "Geister", "net": "transformer",
            "net_args": {"d_model": 32, "n_heads": 2, "n_layers": 2,
                         "memory_len": 8},
        })
        module = env.net()
        variables = init_variables(module, env)
        cfg = normalize_args({
            "env_args": {"env": "Geister"},
            "train_args": {"batch_size": 8, "forward_steps": 6,
                           "burn_in_steps": 2, "observation": True,
                           "seq_attention": "einsum"},
        })
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        roll = StreamingDeviceRollout(
            VectorGeister, module, args, n_lanes=8, k_steps=64
        )
        key = jax.random.PRNGKey(0)
        episodes = []
        for _ in range(4):
            key, sub = jax.random.split(key)
            episodes += roll.generate(variables["params"], sub)
        assert episodes, "no episode finished with the transformer policy"
        ep = episodes[0]
        cols = [decompress_block(b) for b in ep["blocks"]]
        tmask = np.concatenate([c["tmask"] for c in cols])
        assert (tmask.sum(axis=1) == 1.0).all()  # strict alternation held

        store = EpisodeStore(64)
        store.extend(episodes)
        windows = []
        while len(windows) < args["batch_size"]:
            w = store.sample_window(
                args["forward_steps"], args["burn_in_steps"], args["compress_steps"]
            )
            if w is not None:
                windows.append(w)
        batch = make_batch(windows, args)
        ctx = TrainContext(module, args, make_mesh({"dp": -1}))
        tstate = ctx.init_state(variables["params"])
        tstate, metrics = ctx.train_step(tstate, ctx.put_batch(batch), 1e-4)
        m = jax.device_get(metrics)
        assert np.isfinite(m["total"]) and m["dcnt"] > 0

    def test_observation_false_records_actors_only(self):
        """With ``observation: false`` the device path must record turn
        players only (omask == tmask), matching host-generator episodes in
        the same store — the observe_mask hook applies only under
        ``observation: true`` (advisor finding, round 2)."""
        from handyrl_tpu.envs.vector_geister import VectorGeister
        from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

        env = make_env({"env": "Geister"})
        module = env.net()
        variables = init_variables(module, env)
        cfg = normalize_args({
            "env_args": {"env": "Geister"},
            "train_args": {"observation": False},
        })
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        roll = StreamingDeviceRollout(
            VectorGeister, module, args, n_lanes=8, k_steps=32
        )
        key = jax.random.PRNGKey(3)
        episodes = []
        for _ in range(8):
            key, sub = jax.random.split(key)
            episodes += roll.generate(variables["params"], sub)
            if episodes:
                break
        assert episodes, "no Geister episode finished in 256 plies"
        cols = [decompress_block(b) for b in episodes[0]["blocks"]]
        tmask = np.concatenate([c["tmask"] for c in cols])
        omask = np.concatenate([c["omask"] for c in cols])
        np.testing.assert_array_equal(omask, tmask)


class TestVectorParallelTicTacToe:
    """Streaming rollout on the simultaneous-move TicTacToe variant:
    device games must replay exactly through the host rules."""

    def _episodes(self, n_calls=6, n_lanes=24, k_steps=6):
        from handyrl_tpu.envs.vector_parallel_tictactoe import VectorParallelTicTacToe
        from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

        env = make_env({"env": "ParallelTicTacToe"})
        module = env.net()
        variables = init_variables(module, env)
        cfg = normalize_args({
            "env_args": {"env": "ParallelTicTacToe"},
            "train_args": {"batch_size": 8, "forward_steps": 4,
                           "turn_based_training": False, "observation": False},
        })
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        roll = StreamingDeviceRollout(
            VectorParallelTicTacToe, module, args, n_lanes=n_lanes, k_steps=k_steps
        )
        key = jax.random.PRNGKey(11)
        episodes = []
        for _ in range(n_calls):
            key, sub = jax.random.split(key)
            episodes += roll.generate(variables["params"], sub)
        return env, args, episodes

    def test_replays_through_host_rules(self):
        env, args, episodes = self._episodes()
        assert len(episodes) > 20
        checked_steps = 0
        for ep in episodes:
            cols = [decompress_block(b) for b in ep["blocks"]]
            obs = np.concatenate([c["obs"] for c in cols])
            action = np.concatenate([c["action"] for c in cols])
            tmask = np.concatenate([c["tmask"] for c in cols])
            amask = np.concatenate([c["amask"] for c in cols])
            T = ep["steps"]
            # rebuild the board-before-step from player 0's view planes
            boards = (obs[:, 0, 1] - obs[:, 0, 2]).reshape(T, 9)  # +1/-1 stones
            env.reset()
            for t in range(T):
                assert (tmask[t] == 1.0).all()  # both players act every step
                np.testing.assert_array_equal(env.cells, boards[t])
                # active rows carry the empty-cell legal mask
                np.testing.assert_array_equal(
                    amask[t, 0] == 0.0, env.cells == 0
                )
                if t + 1 < T:
                    diff = boards[t + 1] - boards[t]
                    placed = np.flatnonzero(diff)
                    assert len(placed) == 1
                    chooser = 0 if diff[placed[0]] > 0 else 1
                    assert action[t, chooser] == placed[0]
                    env._apply(int(placed[0]), chooser)
                    assert not env.terminal()
                    checked_steps += 1
                else:
                    # final step: the true chooser's action must end the
                    # game with the recorded outcome
                    found = False
                    for chooser in (0, 1):
                        trial = make_env(args["env"])
                        trial.reset()
                        trial.cells = boards[t].astype(trial.cells.dtype).copy()
                        # host terminal() counts history; seed it with the
                        # stones already on the board
                        trial.history = [(0, 0)] * int((trial.cells != 0).sum())
                        trial._apply(int(action[t, chooser]), chooser)
                        if trial.terminal() and trial.outcome() == ep["outcome"]:
                            found = True
                            break
                    assert found, (t, ep["outcome"])
        assert checked_steps > 50

    def test_chooser_is_fair(self):
        """The applied action comes from each player ~half the time."""
        env, args, episodes = self._episodes(n_calls=8)
        by = [0, 0]
        for ep in episodes:
            cols = [decompress_block(b) for b in ep["blocks"]]
            obs = np.concatenate([c["obs"] for c in cols])
            T = ep["steps"]
            boards = (obs[:, 0, 1] - obs[:, 0, 2]).reshape(T, 9)
            for t in range(T - 1):
                diff = boards[t + 1] - boards[t]
                placed = np.flatnonzero(diff)
                by[0 if diff[placed[0]] > 0 else 1] += 1
        total = sum(by)
        assert total > 100
        assert 0.35 < by[0] / total < 0.65


def test_learner_rejects_observer_training_without_observer_views(tmp_path, monkeypatch):
    """observation: true + device rollouts must fail at startup for vector
    envs that record acting players only (HungryGeese) — and be accepted
    for ones with an observe_mask hook (Geister, covered by the CLI run)."""
    import pytest

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "batch_size": 8, "forward_steps": 4, "observation": True,
            "turn_based_training": False, "device_rollout_games": 16,
            "worker": {"num_parallel": 1},
        },
    })
    with pytest.raises(ValueError, match="observer views"):
        Learner(args)


def test_learner_with_device_rollouts(tmp_path, monkeypatch):
    """Full learner stack with on-device generation: device batches feed
    the store and drive the epoch cadence; host workers keep evaluating."""
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    args = normalize_args({
        "env_args": {"env": "TicTacToe"},
        "train_args": {
            "batch_size": 8,
            "forward_steps": 4,
            "minimum_episodes": 40,
            "update_episodes": 40,
            "maximum_episodes": 400,
            "epochs": 2,
            "num_batchers": 1,
            "eval_rate": 0.2,
            "device_rollout_games": 32,
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(args)
    learner.run()

    assert os.path.exists("models/2.ckpt")
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert len(records) >= 2
    assert learner.num_returned_episodes >= 80
