"""Test bootstrap: force an 8-device virtual CPU platform so multi-chip
sharding paths are exercised without TPU hardware.

jax may already be imported by site customizations before this runs, but
backends initialize lazily, so ``jax.config.update`` still takes effect as
long as no computation has run yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# NOTE: do NOT enable the jax persistent compilation cache
# (JAX_COMPILATION_CACHE_DIR) for this suite.  On this jaxlib's CPU
# backend an executable RELOADED from the cache can differ from the
# fresh compile: test_sentinel's in-step skip deterministically loses
# its unconditional steps+1 increment on a warm cache (cold run passes,
# warm rerun of the same test fails), so cached executables are not
# trustworthy here.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax

jax.config.update("jax_platforms", "cpu")
