"""Elastic fleet (docs/serving.md §Elastic fleet).

Pinned smallest-first:

* the pure ``AutoscaleDecider`` hysteresis contract — SLO-breach
  scale-up, never-stack-cold-replicas, cooldown, sustained-calm
  scale-down — socket-free;
* warm-then-admit — a connected replica with no published engine is
  WARMING, not live: it takes zero traffic until its probe passes, and
  a fleet with no warm replica refuses to serve at all;
* the zero-loss retire: seal → drain → migrate the whole SessionCache
  (device residents AND spill-ring entries) to a successor, sessions
  continue BIT-IDENTICAL to an unmigrated control with zero counted
  affinity misses;
* the slow e2es: a load storm scaling the fleet up (no request shed
  into a cold engine) and back down (sessions migrated off the retiring
  replica), and a SIGTERM-preempted subprocess replica handing its
  sessions off inside its drain deadline and exiting 75.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from handyrl_tpu.envs import make_env
from handyrl_tpu.fleet import FleetRouter, ReplicaSpec
from handyrl_tpu.fleet.autoscale import AutoscaleDecider
from handyrl_tpu.models import init_variables
from handyrl_tpu.serving import ModelRouter, ServingClient, ServingServer

pytestmark = pytest.mark.fleet

# tests/ is not a package: the small fleet fixtures are duplicated from
# tests/test_fleet.py rather than imported
SERVING_CFG = {
    "port": 0,
    "max_models": 3,
    "slo_ms": 2000.0,
    "shed_policy": "none",
    "max_batch": 8,
    "max_wait_ms": 1.0,
    "warm_buckets": [1, 4, 8],
    "queue_bound": 256,
    "recv_timeout": 0.0,
    "watch_interval": 0.0,
    "stats_interval": 0.0,
    "session_capacity": 64,
    "session_spill": 256,
}

FLEET_CFG = {
    "port": 0,
    "stats_poll_s": 0.2,
    "replica_stall_s": 5.0,
    "rejoin_backoff_s": 0.2,
    "rejoin_backoff_max_s": 1.0,
    "stats_interval": 0.0,
}


def _env_model(name):
    env = make_env({"env": name})
    module = env.net()
    env.reset()
    obs = env.observation(env.players()[0])
    params = init_variables(module, env, seed=1)["params"]
    return module, obs, params


def _start_server(module, obs, params, tmp_path, **cfg_overrides):
    cfg = dict(SERVING_CFG, **cfg_overrides)
    router = ModelRouter(module, obs, cfg, model_dir=str(tmp_path))
    if params is not None:
        router.publish(1, params)
    return ServingServer(router, cfg).run()


def _fleet(server_ports, connect_timeout=5.0, **overrides):
    cfg = dict(FLEET_CFG, **overrides)
    cfg["replicas"] = [
        e if isinstance(e, dict) else f"127.0.0.1:{e}" for e in server_ports
    ]
    return FleetRouter(cfg).run(connect_timeout=connect_timeout)


def _wait_for(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


# ---------------------------------------------------------------------------
# AutoscaleDecider (socket-free hysteresis)
# ---------------------------------------------------------------------------


_DECIDER_CFG = {
    "min_replicas": 1,
    "max_replicas": 3,
    "shed_slo": 0.01,
    "depth_high": 8.0,
    "depth_low": 1.0,
    "scale_down_after_s": 5.0,
    "cooldown_s": 2.0,
}


def test_decider_scales_up_on_slo_breach_with_cooldown():
    d = AutoscaleDecider(_DECIDER_CFG)
    # shed rate over the SLO: up
    assert d.decide(10.0, 1, 0, shed_rate=0.05, depth_mean=0.0) == "up"
    # still breaching inside the cooldown: hold
    assert d.decide(11.0, 2, 0, shed_rate=0.05, depth_mean=0.0) is None
    # cooldown expired but the previous spawn is still warming: never
    # stack cold replicas
    assert d.decide(13.0, 2, 1, shed_rate=0.05, depth_mean=0.0) is None
    # warm now: up again
    assert d.decide(14.0, 2, 0, shed_rate=0.05, depth_mean=0.0) == "up"
    # at max_replicas: hold no matter the load
    assert d.decide(17.0, 3, 0, shed_rate=0.9, depth_mean=99.0) is None


def test_decider_scales_up_on_depth_pressure():
    d = AutoscaleDecider(_DECIDER_CFG)
    # depth crosses before shedding starts — scale on pressure, not pain
    assert d.decide(10.0, 1, 0, shed_rate=0.0, depth_mean=9.0) == "up"


def test_decider_restores_floor_unconditionally():
    d = AutoscaleDecider(_DECIDER_CFG)
    assert d.decide(10.0, 1, 0, shed_rate=0.05, depth_mean=0.0) == "up"
    # below min_replicas (replica lost): restore the floor even inside
    # the cooldown, even with zero load — the floor IS the contract
    assert d.decide(10.5, 0, 0, shed_rate=0.0, depth_mean=0.0) == "up"


def test_decider_scales_down_only_after_sustained_calm():
    d = AutoscaleDecider(_DECIDER_CFG)
    # calm but not yet sustained: hold
    assert d.decide(10.0, 2, 0, shed_rate=0.0, depth_mean=0.0) is None
    assert d.decide(13.0, 2, 0, shed_rate=0.0, depth_mean=0.0) is None
    # a load blip resets the calm clock
    assert d.decide(14.0, 2, 0, shed_rate=0.0, depth_mean=4.0) is None
    assert d.decide(15.0, 2, 0, shed_rate=0.0, depth_mean=0.0) is None
    assert d.decide(18.0, 2, 0, shed_rate=0.0, depth_mean=0.0) is None
    # sustained 5s of calm since the blip: down
    assert d.decide(20.1, 2, 0, shed_rate=0.0, depth_mean=0.0) == "down"
    # never below the floor, no matter how calm
    assert d.decide(30.0, 1, 0, shed_rate=0.0, depth_mean=0.0) is None
    assert d.decide(40.0, 1, 0, shed_rate=0.0, depth_mean=0.0) is None


# ---------------------------------------------------------------------------
# warm-then-admit
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~3.5s of socket warm-probe waits; CI fleet step runs it
def test_cold_replica_is_warming_not_live_until_published(tmp_path):
    """A connected replica with NO published engine takes zero traffic:
    it shows as warming, every request lands on the warm replica, and
    publishing flips it to admitted without operator help."""
    module, obs, params = _env_model("TicTacToe")
    warm = _start_server(module, obs, params, tmp_path / "warm")
    cold_cfg = dict(SERVING_CFG)
    cold_router = ModelRouter(module, obs, cold_cfg,
                              model_dir=str(tmp_path / "cold"))
    cold = ServingServer(cold_router, cold_cfg).run()  # nothing published
    fleet = _fleet([warm.bound_port, cold.bound_port], stats_poll_s=0.05)
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        stats = client.stats()
        assert stats["fleet_replicas_live"] == 2
        assert stats["fleet_replicas_warming"] == 1
        # the cold replica's engine serves nothing while it warms
        for _ in range(6):
            assert client.infer(obs) is not None
        cold_rep = next(r for r in fleet._reps()
                        if r.spec.port == cold.bound_port)
        assert not cold_rep.admitted
        assert cold_rep.picked == 0, "a warming replica takes no traffic"
        # publish: the admit probe notices and opens it to traffic
        cold_router.publish(1, params)
        _wait_for(lambda: cold_rep.admitted, 10.0,
                  "cold replica admission after publish")
        assert client.stats()["fleet_replicas_warming"] == 0
    finally:
        client.close()
        fleet.shutdown()
        warm.shutdown()
        cold.shutdown()


def test_fleet_refuses_to_serve_with_no_warm_replica(tmp_path):
    """The startup gate: an all-cold fleet must fail LOUDLY instead of
    binding and shedding the first requests into compile pauses."""
    module, obs, _ = _env_model("TicTacToe")
    cfg = dict(SERVING_CFG)
    router = ModelRouter(module, obs, cfg, model_dir=str(tmp_path))
    cold = ServingServer(router, cfg).run()  # never published
    try:
        with pytest.raises(ConnectionError, match="warm"):
            _fleet([cold.bound_port], connect_timeout=1.5, stats_poll_s=0.05)
    finally:
        cold.shutdown()


# ---------------------------------------------------------------------------
# planned retire: seal -> drain -> migrate -> stop, zero-loss
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~6s (two Geister engines + lockstep); CI fleet step runs it
def test_planned_retire_migrates_sessions_bit_identical(tmp_path):
    """THE migration acceptance pin: retiring a replica moves its whole
    SessionCache — device residents AND spill-ring entries — to the
    successor; the migrated sessions' next replies are BIT-IDENTICAL to
    unmigrated control sessions with the same history, and the fleet-wide
    affinity-miss count does not move (zero sessions lost)."""
    module, obs, params = _env_model("Geister")
    # session_capacity 1: a replica holding two sessions keeps one
    # device-resident and one in the spill ring — the export must move both
    s1 = _start_server(module, obs, params, tmp_path / "a",
                       session_capacity=1, session_spill=8)
    s2 = _start_server(module, obs, params, tmp_path / "b",
                       session_capacity=1, session_spill=8)
    fleet = _fleet([s1.bound_port, s2.bound_port], stats_poll_s=5.0)
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        # open sessions until the victim owns two (round-robin at equal
        # load spreads them 2/2 over 4 opens)
        sids = [client.open_session() for _ in range(4)]
        by_port = {}
        for sid in sids:
            by_port.setdefault(fleet._affinity[sid].spec.port, []).append(sid)
        assert sorted(len(v) for v in by_port.values()) == [2, 2], by_port
        victim_port = s1.bound_port
        migr_sids, ctrl_sids = by_port[victim_port], by_port[s2.bound_port]

        # identical histories: both replicas hold the same seeded params,
        # so serial batch-1 trajectories are bit-identical across them
        for _ in range(3):
            for sid in sids:
                assert client.infer(obs, sid=sid)["sid"] == sid

        baseline = client.stats()
        miss0 = sum(r["session_affinity_miss"]
                    for r in baseline["replicas"].values())
        victim_rep = next(r for r in fleet._reps()
                          if r.spec.port == victim_port)
        migrated = fleet.retire(victim_rep)
        assert migrated == 2, "both tiers must travel"

        # affinity re-pinned to the survivor; next steps bit-identical
        # with the unmigrated controls (served via session_restored)
        for sid in migr_sids:
            assert fleet._affinity[sid].spec.port == s2.bound_port
        migr_out = [client.infer(obs, sid=sid, timeout=30)["out"]
                    for sid in migr_sids]
        ctrl_out = [client.infer(obs, sid=sid, timeout=30)["out"]
                    for sid in ctrl_sids]
        for a, b in zip(migr_out, ctrl_out):
            assert set(a) == set(b)
            for k in a:
                np.testing.assert_array_equal(np.asarray(a[k]),
                                              np.asarray(b[k]))

        stats = client.stats()
        survivor = stats["replicas"][f"127.0.0.1:{s2.bound_port}"]
        assert survivor["session_migrated_in"] == 2
        assert survivor["session_restored"] >= 2
        miss1 = sum(r["session_affinity_miss"]
                    for r in stats["replicas"].values())
        assert miss1 - miss0 == 0, "a planned retire loses zero sessions"
        assert stats["fleet_migrations"] == 1
        assert stats["fleet_sessions_migrated"] == 2
        assert stats["fleet_migration_ms"] > 0.0
        # the retired replica left the rotation entirely
        assert stats["fleet_replicas"] == 1
        # retire is idempotent: a second call is a no-op
        assert fleet.retire(victim_rep) == 0
    finally:
        client.close()
        fleet.shutdown()
        s1.shutdown()
        s2.shutdown()


def test_retire_without_successor_is_loud_not_wedged(tmp_path):
    """Retiring the LAST stateful replica cannot migrate anywhere: the
    sessions re-open fresh (counted misses on their next touch), the
    retire itself returns 0 and never hangs."""
    module, obs, params = _env_model("Geister")
    s1 = _start_server(module, obs, params, tmp_path / "a")
    fleet = _fleet([s1.bound_port], stats_poll_s=5.0)
    client = ServingClient("127.0.0.1", fleet.bound_port)
    try:
        sid = client.open_session()
        assert client.infer(obs, sid=sid)["sid"] == sid
        rep = fleet._reps()[0]
        t0 = time.monotonic()
        assert fleet.retire(rep) == 0
        assert time.monotonic() - t0 < 10.0, "retire must be bounded"
        assert sid not in fleet._affinity
    finally:
        client.close()
        fleet.shutdown()
        s1.shutdown()


# ---------------------------------------------------------------------------
# load-storm e2e: scale up under pressure (shed-free), back down when calm
# ---------------------------------------------------------------------------


class _InProcFactory:
    """ReplicaFactory over in-process serving servers — the autoscaler's
    spawn/stop seam without process overhead, for the storm e2e."""

    def __init__(self, make_server):
        self._make = make_server
        self._servers = {}
        self.spawned = 0

    def spawn(self):
        server = self._make(self.spawned)
        self.spawned += 1
        spec = ReplicaSpec("127.0.0.1", server.bound_port)
        self._servers[spec.name] = server
        return spec

    def stop(self, spec):
        server = self._servers.pop(spec.name, None)
        if server is not None:
            server.shutdown()

    def close(self):
        servers, self._servers = dict(self._servers), {}
        for server in servers.values():
            server.shutdown()


@pytest.mark.slow
def test_load_storm_scales_up_shed_free_and_back_down(tmp_path):
    """THE elastic acceptance e2e: a request storm drives the autoscaler
    over depth_high -> scale-up; the new replica warms BEFORE admission
    so not one storm request is shed or errored; calm drives scale-down,
    which retires the newest spawned replica THROUGH the migration path
    (its session moves, zero counted losses)."""
    module, obs, params = _env_model("Geister")

    def make_server(n):
        # max_batch 1 keeps queue depth visible under the storm
        return _start_server(module, obs, params, tmp_path / f"r{n}",
                             max_batch=1, max_wait_ms=0.0,
                             warm_buckets=[1])

    factory = _InProcFactory(make_server)
    fleet = FleetRouter(
        {
            "port": 0, "replicas": [], "stats_poll_s": 0.1,
            "replica_stall_s": 10.0, "rejoin_backoff_s": 0.2,
            "rejoin_backoff_max_s": 1.0, "stats_interval": 0.0,
            "autoscale": {
                "enabled": True, "min_replicas": 1, "max_replicas": 2,
                "interval_s": 0.1, "shed_slo": 0.01, "depth_high": 2.0,
                "depth_low": 1.0, "scale_down_after_s": 0.6,
                "cooldown_s": 0.2, "warm_timeout_s": 60.0,
            },
        },
        replica_factory=factory,
    ).run(connect_timeout=60.0)
    client = ServingClient("127.0.0.1", fleet.bound_port)
    stop = threading.Event()
    errors = []
    served = [0]

    def _storm():
        c = ServingClient("127.0.0.1", fleet.bound_port)
        try:
            while not stop.is_set():
                try:
                    c.infer(obs, timeout=30)
                    served[0] += 1
                except Exception as exc:  # any shed/error fails the pin
                    errors.append(repr(exc))
                    return
        finally:
            c.close()

    threads = [threading.Thread(target=_storm, daemon=True)
               for _ in range(12)]
    try:
        assert client.stats()["fleet_replicas_live"] == 1
        for t in threads:
            t.start()
        # the storm must scale the fleet up, and the new replica must be
        # ADMITTED (warm) — not merely spawned
        _wait_for(
            lambda: fleet.scale_ups >= 1 and sum(
                1 for r in fleet._reps() if r.alive and r.admitted) >= 2,
            60.0, "storm scale-up to a second warm replica",
        )
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, f"storm requests must never fail: {errors[:3]}"
        assert served[0] > 0
        stats = client.stats()
        shed = sum(r.get("serve_shed") or 0
                   for r in stats["replicas"].values())
        assert shed == 0, "warm-then-admit means a scale-up sheds nothing"

        # pin a session to the NEWEST spawned replica (the scale-down
        # victim) so the calm-path retire has state to migrate
        victim = [r for r in fleet._reps() if r.spawned][-1]
        sid = None
        for _ in range(8):
            s = client.open_session()
            if fleet._affinity[s] is victim:
                sid = s
                break
        assert sid is not None, "no session landed on the newest replica"
        assert client.infer(obs, sid=sid)["sid"] == sid
        miss0 = sum(r["session_affinity_miss"]
                    for r in client.stats()["replicas"].values())

        # calm: the autoscaler retires the newest spawned replica through
        # the migration path
        _wait_for(lambda: fleet.scale_downs >= 1, 30.0, "calm scale-down")
        _wait_for(lambda: client.stats()["fleet_replicas_live"] == 1, 15.0,
                  "fleet back at the floor")
        assert fleet.sessions_migrated >= 1
        # the migrated session keeps answering, with zero counted losses
        assert client.infer(obs, sid=sid, timeout=30)["sid"] == sid
        miss1 = sum(r["session_affinity_miss"]
                    for r in client.stats()["replicas"].values())
        assert miss1 - miss0 == 0, "scale-down loses zero sessions"
    finally:
        stop.set()
        client.close()
        fleet.shutdown()
        factory.close()


# ---------------------------------------------------------------------------
# preemption e2e: SIGTERM'd subprocess replica drains inside its deadline
# ---------------------------------------------------------------------------


_REPLICA_CHILD = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from handyrl_tpu.config import normalize_args
from handyrl_tpu.serving.server import serve_main

args = normalize_args({
    "env_args": {"env": "Geister"},
    "train_args": {
        "model_dir": sys.argv[1],
        "drain_deadline_seconds": 20.0,
        "serving": {
            "port": 0, "max_models": 3, "shed_policy": "none",
            "max_batch": 8, "max_wait_ms": 1.0, "warm_buckets": [1],
            "watch_interval": 0.0, "stats_interval": 0.0,
            "session_capacity": 64, "session_spill": 256,
        },
    },
})
serve_main(args)
"""


def _spawn_replica_proc(model_dir, fault_after=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("HANDYRL_FAULT_SIGTERM_REPLICA", None)
    if fault_after is not None:
        env["HANDYRL_FAULT_SIGTERM_REPLICA"] = str(fault_after)
    proc = subprocess.Popen(
        [sys.executable, "-c", _REPLICA_CHILD, str(model_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True,
    )
    port = [None]
    lines = []

    def _reader():
        for line in proc.stdout:
            lines.append(line.rstrip())
            if "listening on port" in line and port[0] is None:
                port[0] = int(line.split("listening on port")[1].split()[0])

    threading.Thread(target=_reader, daemon=True).start()
    deadline = time.monotonic() + 120.0
    while port[0] is None and time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                "replica child died before binding:\n" + "\n".join(lines))
        time.sleep(0.05)
    if port[0] is None:
        proc.kill()
        raise AssertionError(
            "replica child never reported its port:\n" + "\n".join(lines))
    return proc, port[0], lines


@pytest.mark.slow
def test_preempted_replica_drains_sessions_and_exits_75(tmp_path):
    """THE preemption acceptance e2e: a replica process SIGTERM'd mid-
    serve (HANDYRL_FAULT_SIGTERM_REPLICA) hands its sessions to a
    survivor inside drain_deadline_seconds and exits 75 (EX_TEMPFAIL);
    the router re-pins affinity and the migrated session's next reply is
    bit-identical to an unmigrated control — zero hangs, zero losses."""
    _, obs_g, _ = _env_model("Geister")
    steps_before_fault = 3
    victim_proc, victim_port, victim_lines = _spawn_replica_proc(
        tmp_path / "victim", fault_after=steps_before_fault)
    surv_proc, surv_port, surv_lines = _spawn_replica_proc(
        tmp_path / "survivor")
    fleet = None
    client = None
    try:
        fleet = _fleet([victim_port, surv_port], connect_timeout=60.0,
                       stats_poll_s=0.3)
        client = ServingClient("127.0.0.1", fleet.bound_port)

        # a session on each replica: one will migrate, one is the control
        sids = [client.open_session() for _ in range(2)]
        owners = {fleet._affinity[s].spec.port: s for s in sids}
        assert set(owners) == {victim_port, surv_port}, \
            "sessions should spread over both replicas"
        migr_sid, ctrl_sid = owners[victim_port], owners[surv_port]

        # identical histories on both (same fresh-init params in both
        # children).  The victim's Nth reply fires its self-SIGTERM.
        for _ in range(steps_before_fault):
            assert client.infer(obs_g, sid=migr_sid, timeout=30)["sid"] \
                == migr_sid
            assert client.infer(obs_g, sid=ctrl_sid, timeout=30)["sid"] \
                == ctrl_sid

        # the preempted child must drain and exit 75 inside its deadline
        t0 = time.monotonic()
        rc = victim_proc.wait(timeout=40.0)
        assert rc == 75, (rc, "\n".join(victim_lines))
        assert time.monotonic() - t0 < 25.0, \
            "drain must respect drain_deadline_seconds"
        _wait_for(lambda: fleet.preempt_drains >= 1, 10.0,
                  "router preemption drain")
        _wait_for(
            lambda: fleet._affinity.get(migr_sid) is not None
            and fleet._affinity[migr_sid].spec.port == surv_port,
            20.0, "affinity re-pinned to the survivor",
        )

        # the migrated session continues bit-identically to the control
        a = client.infer(obs_g, sid=migr_sid, timeout=30)["out"]
        b = client.infer(obs_g, sid=ctrl_sid, timeout=30)["out"]
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))

        stats = client.stats()
        assert stats["fleet_preempt_drains"] == 1
        assert stats["fleet_sessions_migrated"] >= 1
        survivor = stats["replicas"][f"127.0.0.1:{surv_port}"]
        assert survivor["session_migrated_in"] >= 1
        assert survivor["session_affinity_miss"] == 0, \
            "a drained preemption loses zero sessions"
        assert any("exiting 75 for relaunch" in l for l in victim_lines)
    finally:
        if client is not None:
            client.close()
        if fleet is not None:
            fleet.shutdown()
        for proc in (victim_proc, surv_proc):
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
