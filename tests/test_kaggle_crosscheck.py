"""Skip-gated crosscheck of the standalone HungryGeese rules against the
real Kaggle engine (tools/crosscheck_kaggle.py).

The build image cannot install ``kaggle_environments`` (zero egress), so
locally this skips; the CI extras job installs the dep and executes it,
replacing the hand-written parity doc with a machine check (ground truth:
the engine the reference wraps, handyrl/envs/kaggle/hungry_geese.py:67).
"""

import os
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))

if os.environ.get("HANDYRL_REQUIRE_EXTRAS"):
    # CI extras job: a missing/broken dep must FAIL there, not skip —
    # the job exists to execute this leg
    import kaggle_environments  # noqa: F401
else:
    pytest.importorskip(
        "kaggle_environments", reason="kaggle_environments not installed"
    )


def test_hungry_geese_matches_kaggle_engine():
    from crosscheck_kaggle import crosscheck_hungry_geese

    crosscheck_hungry_geese(num_games=10, verbose=False)
