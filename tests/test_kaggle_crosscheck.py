"""Crosscheck of the standalone HungryGeese rules against the real Kaggle
engine (tools/crosscheck_kaggle.py), plus a local validation of the
crosscheck harness itself.

The build image cannot install ``kaggle_environments`` (zero egress), so
the real crosscheck skips locally; the CI extras job installs the dep and
executes it, replacing the hand-written parity doc with a machine check
(ground truth: the engine the reference wraps,
handyrl/envs/kaggle/hungry_geese.py:67).  Because the harness's first
real execution is in CI, its plumbing (state injection, food sync,
status/outcome comparison) is exercised HERE against a fake Kaggle
module backed by a second independent instance of our own engine — a
plumbing bug fails locally, only a genuine rules divergence can fail in
CI.
"""

import os
import random
import sys
import types
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))


def _require_kaggle():
    if os.environ.get("HANDYRL_REQUIRE_EXTRAS"):
        # CI extras job: a missing/broken dep must FAIL there, not skip —
        # the job exists to execute this leg
        import kaggle_environments  # noqa: F401
    else:
        pytest.importorskip(
            "kaggle_environments", reason="kaggle_environments not installed"
        )


def test_hungry_geese_matches_kaggle_engine():
    _require_kaggle()
    from crosscheck_kaggle import crosscheck_hungry_geese

    crosscheck_hungry_geese(num_games=10, verbose=False)


class _FakeKaggleEnv:
    """Duck-types the slice of kaggle_environments' hungry_geese env the
    crosscheck touches — reset(num_agents)/step(action_strings) returning
    per-agent dicts with status/reward/observation — backed by our own
    host rules, so both crosscheck sides step independent engines."""

    def reset(self, num_agents: int):
        import handyrl_tpu.envs.hungry_geese as hg

        assert num_agents == 4
        self._env = hg.Environment()
        self._env.reset()
        return self._obs()

    def step(self, action_strings):
        import handyrl_tpu.envs.hungry_geese as hg

        actions = {
            p: hg.ACTIONS.index(action_strings[p])
            for p in range(4)
            if self._env.active[p]
        }
        self._env.step(actions)
        return self._obs()

    def _obs(self):
        env = self._env
        shared = {
            "geese": [list(g) for g in env.geese],
            "food": list(env.food),
        }
        return [
            {
                "status": "ACTIVE" if env.active[p] else "DONE",
                "reward": env.rank_rewards[p],
                "observation": dict(shared, index=p) if p == 0 else {"index": p},
            }
            for p in range(4)
        ]


def test_crosscheck_harness_plumbing(monkeypatch):
    """Run the real crosscheck loop against the fake Kaggle module: our
    engine on both sides must come out identical, proving the harness's
    injection/sync/compare logic (not the rules — CI does that)."""
    fake = types.ModuleType("kaggle_environments")
    fake.make = lambda name: (_FakeKaggleEnv() if name == "hungry_geese"
                              else None)
    monkeypatch.setitem(sys.modules, "kaggle_environments", fake)

    from crosscheck_kaggle import crosscheck_hungry_geese

    random.seed(202)  # the fake engine's reset/food draws use global random
    crosscheck_hungry_geese(num_games=5, verbose=False)
