"""League training plane tests (handyrl_tpu/league).

Units: the payoff ledger's pairwise accounting, PFSP weighting, the
registry's persistence/verification/capping, the promotion-gate
book-keeping, and the ModelRouter-backed opponent serving.  The
end-to-end acceptance run (the ISSUE 11 bar) trains a TicTacToe league
on the virtual CPU mesh until a >=3-member population exists: PFSP
matches fill the payoff matrix for every active pair, at least one
candidate clears the promotion gate and freezes, and league_* metrics
land in metrics.jsonl.
"""

import json
import os

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.league import (
    ANCHOR,
    CANDIDATE,
    League,
    Matchmaker,
    PayoffMatrix,
    pfsp_weights,
)

pytestmark = pytest.mark.league


# ---------------------------------------------------------------------------
# payoff ledger
# ---------------------------------------------------------------------------


class TestPayoffMatrix:
    def test_pairwise_wins_draws_losses(self):
        p = PayoffMatrix()
        p.record_outcome({0: "a", 1: "b"}, {0: 1.0, 1: -1.0})
        p.record_outcome({0: "a", 1: "b"}, {0: -1.0, 1: 1.0})
        p.record_outcome({0: "a", 1: "b"}, {0: 0.0, 1: 0.0})
        p.record_outcome({0: "a", 1: "b"}, {0: 0.0, 1: 0.0})
        assert p.games("a", "b") == p.games("b", "a") == 4
        # wp_func convention: (1 win + 2 draws/2) / 4
        assert p.win_points("a", "b") == pytest.approx(0.5)
        assert p.win_points("b", "a") == pytest.approx(0.5)
        assert p.matches == 4

    def test_wp_matches_wp_func_convention(self):
        """One ledger, one convention: the PayoffMatrix win points must be
        numerically wp_func over the same outcomes (the tools share it)."""
        from handyrl_tpu.runtime.evaluation import wp_func

        rng = np.random.default_rng(0)
        p = PayoffMatrix()
        totals = {}
        for _ in range(200):
            o = float(rng.choice([-1.0, 0.0, 1.0]))
            p.record_outcome({0: "x", 1: "y"}, {0: o, 1: -o})
            totals[o] = totals.get(o, 0) + 1
        assert p.win_points("x", "y") == pytest.approx(wp_func(totals))

    def test_multiplayer_placements_decompose_pairwise(self):
        """A 4-player rank outcome (HungryGeese scores) records 6 pairwise
        results: every seat beats every lower-ranked seat; ties draw."""
        p = PayoffMatrix()
        names = {0: "a", 1: "b", 2: "c", 3: "d"}
        p.record_outcome(names, {0: 1.0, 1: 1 / 3, 2: -1 / 3, 3: -1.0})
        assert p.win_points("a", "b") == 1.0
        assert p.win_points("a", "d") == 1.0
        assert p.win_points("c", "b") == 0.0
        assert p.win_points("d", "a") == 0.0
        p.record_outcome(names, {0: 0.5, 1: 0.5, 2: -1.0, 3: -1.0})
        assert p.win_points("a", "b") == pytest.approx(0.75)   # win then draw
        assert p.win_points("c", "d") == pytest.approx(0.75)   # win then tie
        assert p.matches == 2

    def test_same_member_both_seats_records_nothing(self):
        p = PayoffMatrix()
        p.record_outcome({0: "a", 1: "a"}, {0: 1.0, 1: -1.0})
        assert p.games("a", "a") == 0
        assert p.matches == 1   # the match still counts as played

    def test_forfeit_only_severs_the_severed(self):
        """Severed seat loses to every survivor; survivor pairs stay
        unrecorded (their game never finished)."""
        p = PayoffMatrix()
        names = {0: "a", 1: "b", 2: "c"}
        p.record_forfeit(names, 1)
        assert p.win_points("a", "b") == 1.0
        assert p.win_points("c", "b") == 1.0
        assert p.win_points("b", "a") == 0.0
        assert p.games("a", "c") == 0
        assert p.forfeits == 1

    def test_aggregate_is_game_weighted(self):
        p = PayoffMatrix()
        for _ in range(9):
            p.record_score("a", "x", 1.0, -1.0)
        p.record_score("a", "y", -1.0, 1.0)
        assert p.aggregate_win_points("a", ["x", "y"]) == pytest.approx(0.9)

    def test_roundtrip_and_adopt(self):
        p = PayoffMatrix()
        p.record_score(CANDIDATE, "x", 1.0, -1.0)
        q = PayoffMatrix.from_dict(p.to_dict())
        assert q.win_points(CANDIDATE, "x") == 1.0
        q.adopt(CANDIDATE, "main-3")
        assert q.win_points("main-3", "x") == 1.0
        assert q.win_points(CANDIDATE, "x") is None
        assert q.win_points("x", "main-3") == 0.0

    def test_elo_orders_and_anchors(self):
        p = PayoffMatrix()
        for _ in range(20):
            p.record_score("strong", ANCHOR, 1.0, -1.0)
            p.record_score("weak", ANCHOR, -1.0, 1.0)
        elo = p.elo(["strong", "weak", ANCHOR], anchor=ANCHOR)
        assert elo[ANCHOR] == 0.0
        assert elo["strong"] > 0 > elo["weak"]


class TestPFSP:
    def test_weightings(self):
        assert pfsp_weights([0.5], "var")[0] == pytest.approx(0.25)
        assert pfsp_weights([1.0], "hard")[0] == pytest.approx(1e-3)  # floored
        assert pfsp_weights([0.0], "hard")[0] == pytest.approx(1.0)
        assert pfsp_weights([0.2, 0.9], "even") == [1.0, 1.0]
        # unplayed -> 0.5, the max of var weighting: new members get probed
        w = pfsp_weights([None, 0.95], "var")
        assert w[0] > w[1]
        with pytest.raises(ValueError):
            pfsp_weights([0.5], "nope")

    def test_matchmaker_prefers_near_peers(self):
        p = PayoffMatrix()
        for _ in range(50):
            p.record_score(CANDIDATE, "solved", 1.0, -1.0)   # p = 1.0
            p.record_score(CANDIDATE, "peer", 1.0, -1.0)
            p.record_score(CANDIDATE, "peer", -1.0, 1.0)     # p = 0.5
        mm = Matchmaker(p, "var", seed=1)
        draws = [mm.sample_opponent(CANDIDATE, ["solved", "peer"]) for _ in range(300)]
        assert draws.count("peer") > 0.9 * len(draws)
        assert mm.sample_opponent(CANDIDATE, []) is None

    def test_probe_quota_prevents_starvation(self):
        """One decisive game must not starve a member forever: below
        min_games the sampler probes uniformly, so the coverage half of
        the promotion gate is always reachable (the bug class: p=1.0
        after a single win floors the 'var' weight)."""
        p = PayoffMatrix()
        p.record_score(CANDIDATE, "anchor", 1.0, -1.0)      # p pinned at 1.0
        for _ in range(50):
            p.record_score(CANDIDATE, "peer", 1.0, -1.0)
            p.record_score(CANDIDATE, "peer", -1.0, 1.0)
        mm = Matchmaker(p, "var", seed=2)
        draws = [
            mm.sample_opponent(CANDIDATE, ["anchor", "peer"], min_games=3)
            for _ in range(50)
        ]
        assert draws.count("anchor") == 50                   # under quota: probed
        # once the quota is met, PFSP takes over again
        p.record_score(CANDIDATE, "anchor", 1.0, -1.0)
        p.record_score(CANDIDATE, "anchor", 1.0, -1.0)
        draws = [
            mm.sample_opponent(CANDIDATE, ["anchor", "peer"], min_games=3)
            for _ in range(200)
        ]
        # smoothing keeps the 3-0 anchor sampled occasionally (p pulled
        # toward 0.5), but the near-peer dominates the draw
        assert draws.count("peer") > draws.count("anchor")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestLeagueRegistry:
    def test_fresh_league_seeds_anchor(self, tmp_path):
        lg = League(str(tmp_path))
        assert ANCHOR in lg.members
        assert lg.members[ANCHOR].role == "anchor"
        assert [m.name for m in lg.opponent_pool()] == [ANCHOR]

    def test_freeze_persist_resume(self, tmp_path):
        lg = League(str(tmp_path))
        lg.payoff.record_score(CANDIDATE, ANCHOR, 1.0, -1.0)
        lg.freeze_candidate(3, steps=123)
        lg2 = League(str(tmp_path))
        assert set(lg2.members) == {ANCHOR, "main-3"}
        assert lg2.promotions == 1
        # the candidate's books moved to the frozen name and persisted
        assert lg2.payoff.win_points("main-3", ANCHOR) == 1.0
        assert lg2.frozen_epochs() == [3]

    def test_load_drops_unverifiable_member(self, tmp_path, capsys):
        from handyrl_tpu.runtime.checkpoint import record_snapshot

        lg = League(str(tmp_path))
        lg.add("main-7", 7)
        lg.save()
        # manifest records epoch 7 but the snapshot bytes are wrong
        (tmp_path / "7.ckpt").write_bytes(b"corrupt")
        record_snapshot(str(tmp_path), 7, 1, {"7.ckpt": (0xDEAD, 999)})
        lg2 = League(str(tmp_path))
        assert "main-7" not in lg2.members
        assert "digest" in capsys.readouterr().out

    def test_unreadable_registry_fails_loudly(self, tmp_path):
        """An EXISTING but unreadable LEAGUE.json must refuse to start a
        fresh league: an empty registry empties the GC pin set and the
        next gc_snapshots pass would permanently delete the frozen
        members' snapshots.  Only a MISSING file means fresh."""
        lg = League(str(tmp_path))
        lg.add("main-2", 2)
        lg.save()
        path = tmp_path / "LEAGUE.json"
        saved = path.read_bytes()
        # a directory at the registry path: open() raises IsADirectoryError
        # (an OSError that is not FileNotFoundError) for ANY uid — chmod
        # tricks don't block root, which CI may run as
        path.unlink()
        path.mkdir()
        try:
            with pytest.raises(RuntimeError, match="cannot be read"):
                League(str(tmp_path))
        finally:
            path.rmdir()
            path.write_bytes(saved)
        assert "main-2" in League(str(tmp_path)).members

    def test_non_owner_never_writes(self, tmp_path):
        """Coordinator-only registry ownership (the checkpoint
        discipline): a non-owner league keeps its in-memory state but
        save() is a no-op."""
        lg = League(str(tmp_path))
        lg.owner = False
        lg.add("main-1", 1)
        lg.save()
        assert not (tmp_path / "LEAGUE.json").exists()

    def test_pool_caps_but_keeps_anchor_and_newest(self, tmp_path):
        lg = League(str(tmp_path), {"max_population": 3})
        for epoch in (1, 2, 3, 4):
            lg.add(f"main-{epoch}", epoch)
        pool = [m.name for m in lg.opponent_pool()]
        assert pool == [ANCHOR, "main-3", "main-4"]
        # retired members' snapshots stay pinned for the books
        assert lg.frozen_epochs() == [1, 2, 3, 4]

    def test_reserved_and_duplicate_names_refused(self, tmp_path):
        lg = League(str(tmp_path))
        with pytest.raises(ValueError, match="reserved"):
            lg.add(CANDIDATE, 5)
        lg.add("main-5", 5)
        with pytest.raises(ValueError, match="already"):
            lg.add("main-5", 5)
        with pytest.raises(ValueError, match="role"):
            lg.add("weird", 6, role="boss")


def test_learner_gc_call_sites_all_pass_pin():
    """EVERY gc_snapshots call in the learner must carry the pin set —
    the epoch-boundary call and the preemption-drain call alike: a
    SIGTERM drain that GCs without pins would permanently delete frozen
    population members' snapshots (found in review)."""
    import ast
    import inspect

    from handyrl_tpu.runtime import learner as learner_mod

    tree = ast.parse(inspect.getsource(learner_mod))
    calls = [
        node for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and getattr(node.func, "id", getattr(node.func, "attr", None))
        == "gc_snapshots"
    ]
    assert calls, "expected gc_snapshots call sites in runtime/learner.py"
    for call in calls:
        assert any(kw.arg == "pin" for kw in call.keywords), (
            f"gc_snapshots call at line {call.lineno} without pin="
        )


def test_gc_snapshots_pins_league_epochs(tmp_path):
    """keep_checkpoints GC must never collect a frozen member's snapshot:
    the pin parameter (fed by LeagueLearner._gc_pinned) exempts them."""
    from handyrl_tpu.runtime.checkpoint import gc_snapshots

    for e in range(1, 8):
        (tmp_path / f"{e}.ckpt").write_bytes(b"x" * 8)
    removed = gc_snapshots(str(tmp_path), keep=2, pin=(3, 4))
    assert set(removed) == {1, 2, 5}
    assert sorted(int(p.name.split(".")[0]) for p in tmp_path.glob("*.ckpt")) == [3, 4, 6, 7]


# ---------------------------------------------------------------------------
# learner integration
# ---------------------------------------------------------------------------


def _league_cfg(tmp_path, **train_overrides):
    train = {
        "batch_size": 8,
        "forward_steps": 4,
        "update_episodes": 8,
        "minimum_episodes": 8,
        "maximum_episodes": 500,
        "num_batchers": 0,
        "batch_pipeline": "thread",
        "epochs": 2,
        "eval_rate": 0.0,
        "worker": {"num_parallel": 2},
        "metrics_path": os.path.join(str(tmp_path), "metrics.jsonl"),
        "model_dir": os.path.join(str(tmp_path), "models"),
        "league": {"promote_winrate": 0.52, "promote_games": 4,
                   "selfplay_rate": 0.25},
    }
    train.update(train_overrides)
    return normalize_args({"env_args": {"env": "TicTacToe"}, "train_args": train})


def test_league_learner_assigns_pfsp_matches(tmp_path):
    """Role assignment: with a frozen pool, generation jobs split between
    pure self-play and candidate-vs-member matches with rotated seats and
    the member's epoch stamped on the opponent seats."""
    from handyrl_tpu.league.learner import LeagueLearner

    cfg = _league_cfg(tmp_path)
    learner = LeagueLearner(cfg)
    try:
        learner.league.add("main-0", 0)  # epoch-0 member: no snapshot needed
        learner.model_epoch = 1          # pretend one epoch trained
        modes = {"selfplay": 0, "match": 0}
        seats_seen = set()
        for _ in range(600):
            args = learner._assign_role()
            if args["role"] != "g":
                # the effective eval-rate floor (update_episodes**-0.15)
                # interleaves eval jobs; league changes only 'g' jobs
                assert "league" not in args
                continue
            meta = args["league"]
            modes[meta["mode"]] += 1
            if meta["mode"] == "match":
                cand = [p for p, n in meta["seats"].items() if n == CANDIDATE]
                assert len(cand) == 1
                assert args["player"] == cand
                seats_seen.add(cand[0])
                for p, name in meta["seats"].items():
                    want = 1 if name == CANDIDATE else 0
                    assert args["model_id"][p] == want
        assert modes["match"] > modes["selfplay"] > 0
        assert seats_seen == {0, 1}      # first/second balanced
    finally:
        learner.model_server.stop()
        learner.trainer.stop()


def test_league_feed_masks_opponent_and_records_payoff(tmp_path):
    """feed_episodes on a league match must (a) record the pairwise
    outcome under the seat names and (b) zero the opponent's tmask/omask
    so only the candidate's steps train."""
    from handyrl_tpu.league.learner import LeagueLearner
    from handyrl_tpu.runtime.replay import compress_block, decompress_block

    cfg = _league_cfg(tmp_path)
    learner = LeagueLearner(cfg)
    try:
        T, P, A = 4, 2, 9
        cols = {
            "obs": np.ones((T, P, 3, 3, 3), np.float32),
            "prob": np.full((T, P), 0.5, np.float32),
            "action": np.zeros((T, P), np.int32),
            "amask": np.zeros((T, P, A), np.float32),
            "value": np.ones((T, P), np.float32),
            "reward": np.zeros((T, P), np.float32),
            "ret": np.zeros((T, P), np.float32),
            "tmask": np.ones((T, P), np.float32),
            "omask": np.ones((T, P), np.float32),
            "turn": np.zeros(T, np.int32),
        }
        episode = {
            "args": {
                "player": [1],
                "model_id": {0: 0, 1: 1},
                "league": {"mode": "match",
                           "seats": {0: "main-0", 1: CANDIDATE}},
            },
            "steps": T,
            "players": [0, 1],
            "outcome": {0: -1.0, 1: 1.0},
            "blocks": [compress_block(cols)],
        }
        learner.feed_episodes([episode, None])
        assert learner.league.payoff.win_points(CANDIDATE, "main-0") == 1.0
        assert learner.league.payoff.win_points("main-0", CANDIDATE) == 0.0
        out = decompress_block(episode["blocks"][0])
        assert out["tmask"][:, 1].tolist() == [1.0] * T       # candidate kept
        assert out["tmask"][:, 0].tolist() == [0.0] * T       # opponent zeroed
        assert out["omask"][:, 0].tolist() == [0.0] * T
        assert out["prob"][:, 0].tolist() == [0.5] * T        # data intact
    finally:
        learner.model_server.stop()
        learner.trainer.stop()


def test_league_model_server_routes_frozen_through_router(tmp_path, monkeypatch):
    """Frozen epochs resolve to resident router engines (one disk load,
    reused), latest keeps the shared engine, id 0 stays the RandomModel,
    and a missing snapshot substitutes latest COUNTED."""
    import jax

    from handyrl_tpu.envs import make_env
    from handyrl_tpu.league.learner import LeagueModelServer, RouterOpponent
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    monkeypatch.chdir(tmp_path)
    cfg = _league_cfg(tmp_path)
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    variables = init_variables(module, env)
    args = dict(cfg["train_args"])
    args["model_dir"] = str(tmp_path / "models")
    server = LeagueModelServer(module, env, args)
    # the whole active pool must stay resident (+1 for the pinned latest):
    # the serving default max_models=4 would thrash evict/cold-reload
    assert server._router.max_models >= args["league"]["max_population"] + 1
    try:
        params = variables["params"]
        save_epoch_snapshot(args["model_dir"], 1, params, {"note": 1}, 1)
        server.publish(1, params)
        server.publish(2, params)
        assert isinstance(server.get(1), RouterOpponent)
        env.reset()
        obs = env.observation(0)
        out = server.get(1).inference(obs)
        assert np.shape(np.asarray(out["policy"]))[-1] == 9
        assert 1 in server._router.routes()
        # latest (>= current) keeps the shared engine; 0 is random
        assert not isinstance(server.get(2), RouterOpponent)
        assert server.get(0) is server._random
        # a GC'd epoch substitutes latest, counted
        before = server.substituted_snapshots
        out = server.get(1)           # resident: no substitution
        out.inference(obs)
        missing = RouterOpponent(server, 1)
        # drop the snapshot file, evict the resident engine, re-resolve
        os.unlink(os.path.join(args["model_dir"], "1.ckpt"))
        server._router._engines.pop(1).stop()
        missing.inference(obs)
        assert server.substituted_snapshots == before + 1
    finally:
        server.stop()


def test_league_learner_refuses_future_members(tmp_path):
    """A league whose members reference epochs newer than the resumed
    model must fail loudly at startup (those matches would silently run
    against LATEST params and poison the books)."""
    from handyrl_tpu.league.learner import LeagueLearner

    cfg = _league_cfg(tmp_path)
    lg = League(os.path.join(str(tmp_path), "models"))
    lg.add("main-9", 9)
    lg.save()
    with pytest.raises(ValueError, match="main-9"):
        LeagueLearner(cfg)


def test_league_end_to_end(tmp_path, monkeypatch):
    """ISSUE 11 acceptance: a TicTacToe league on the virtual CPU mesh
    grows a >=3-member population (anchor + >=2 frozen) through the
    promotion gate, the payoff matrix fills for every active pair, and
    league_* metrics land in metrics.jsonl."""
    from handyrl_tpu.league.learner import LeagueLearner

    monkeypatch.chdir(tmp_path)
    # the bar sits below the random-vs-random seat-balanced wp (~0.5) so
    # the GATE MECHANICS (coverage requirement, freeze, books hand-off,
    # GC pin) are what this run exercises within a CI-sized epoch budget
    # — candidate strength vs the bar is the league soak's concern
    cfg = _league_cfg(
        tmp_path,
        epochs=8,
        update_episodes=24,
        minimum_episodes=16,
        league={"promote_winrate": 0.4, "promote_games": 3,
                "selfplay_rate": 0.15, "pfsp_weighting": "var"},
    )
    learner = LeagueLearner(cfg)
    assert learner.run() == 0

    # population: anchor + >=2 promoted members
    members = learner.league.members
    frozen = [m for m in members.values() if m.role == "frozen"]
    assert len(members) >= 3, sorted(members)
    assert len(frozen) >= 2, sorted(members)
    assert learner.league.promotions >= 2

    # payoff coverage: the matrix filled for every pair ACTIVE at each
    # generation — a member frozen at epoch K inherited the candidate's
    # books, which the gate required to cover the whole pool of its time
    # (the anchor + every earlier-frozen member)
    payoff = learner.league.payoff
    for i, m in enumerate(sorted(frozen, key=lambda m: m.epoch)):
        earlier = [ANCHOR] + [
            x.name for x in sorted(frozen, key=lambda m: m.epoch)[:i]
        ]
        assert payoff.coverage(m.name, earlier) == 1.0, (m.name, earlier)
        assert all(payoff.games(m.name, b) >= 3 for b in earlier)

    # the league persisted and re-loads with books intact
    lg2 = League(os.path.join(str(tmp_path), "models"))
    assert set(lg2.members) == set(members)
    assert lg2.payoff.matches == payoff.matches

    # league_* metrics in metrics.jsonl
    records = [json.loads(l) for l in open(cfg["train_args"]["metrics_path"])]
    assert records
    last = records[-1]
    for key in ("league_population", "league_pool", "league_matches",
                "league_payoff_coverage", "league_promotions"):
        assert key in last, key
    assert last["league_population"] >= 3
    assert last["league_matches"] > 0
    assert last["league_promotions"] >= 2
    assert any(r.get("league_elo_spread") is not None for r in records)
