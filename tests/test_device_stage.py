"""DeviceEpisodeStage / DeviceBatchPipeline (host-bypass assembly) tests.

The bar (ISSUE 6 acceptance, same as tests/test_device_replay.py): a
window sampled and assembled ON DEVICE from staged host-born episodes
must equal, key by key, the batch the host path (EpisodeStore window ->
make_batch) builds for the SAME episode, window start, and target player.
Both paths consume identical generator episodes, so every difference is
an assembly bug, not sampling noise.
"""

import random
import threading

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.parallel import TrainContext, make_mesh
from handyrl_tpu.runtime import codec
from handyrl_tpu.runtime.batch import make_batch
from handyrl_tpu.runtime.device_batch import DeviceBatchPipeline
from handyrl_tpu.runtime.device_replay import DeviceEpisodeStage
from handyrl_tpu.runtime.generation import Generator
from handyrl_tpu.runtime.replay import EpisodeStore
from handyrl_tpu.utils import tree_map

pytestmark = pytest.mark.pipeline


def _targs(env="HungryGeese", **over):
    base = {"mesh": {"dp": 1}}
    base.update(over)
    cfg = normalize_args({"env_args": {"env": env}, "train_args": base})
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    return args


def _gen_episodes(env_name, n, targs, seed=0):
    random.seed(seed)
    env = make_env({"env": env_name})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env, seed=seed))
    gen = Generator(env, targs)
    models = {p: model for p in env.players()}
    gen_args = {"player": env.players(), "model_id": {p: 1 for p in env.players()}}
    eps = []
    while len(eps) < n:
        ep = gen.generate(models, gen_args)
        if ep is not None:
            eps.append(ep)
    return env, module, eps


def _stage_with_episodes(env_name="HungryGeese", n=40, lanes=4, chunk=8,
                         slots=256, **over):
    over.setdefault("batch_size", 8)
    over.setdefault("forward_steps", 8)
    targs = _targs(env_name, **over)
    env, module, eps = _gen_episodes(env_name, n, targs)
    mesh = make_mesh({"dp": 1})
    stage = DeviceEpisodeStage(
        module, targs, mesh, n_lanes=lanes, slots=slots, chunk_steps=chunk,
        track_episodes=True,
    )
    for ep in eps:
        stage.add_episode(ep)
    stage.flush()
    stage.drain()
    return {"stage": stage, "episodes": eps, "args": targs,
            "module": module, "env": env, "mesh": mesh}


def _host_window(ep, train_start, args):
    """The exact sample_window dict (replay.py) for a forced train_start."""
    fwd, cs = args["forward_steps"], args["compress_steps"]
    steps = ep["steps"]
    start = max(0, train_start - args["burn_in_steps"])
    end = min(train_start + fwd, steps)
    first_block = start // cs
    last_block = (end - 1) // cs + 1
    return {
        "args": ep["args"],
        "outcome": np.asarray(
            [ep["outcome"][p] for p in ep["players"]], np.float32
        ),
        "players": ep["players"],
        "blocks": ep["blocks"][first_block:last_block],
        "base": first_block * cs,
        "start": start,
        "end": end,
        "train_start": train_start,
        "total": steps,
    }


def _check_windows(data, monkeypatch, n, seed=3):
    """Key-by-key equality of stage-assembled windows vs make_batch on the
    same (episode, train_start, target player) — test_device_replay's bar,
    mapped through the stage's lane-span ledger."""
    stage, args = data["stage"], data["args"]
    replay = stage.replay
    S = stage.slots
    G = int(jax.device_get(replay.rings["g"]))

    batch, info = replay.sample(jax.random.PRNGKey(seed), n, with_info=True)
    batch = tree_map(np.asarray, batch)

    for i in range(n):
        lane, slot, player = (
            int(info["lane"][i]), int(info["slot"][i]), int(info["player"][i])
        )
        gs0 = G - 1 - ((G - 1 - slot) % S)     # global step held by the slot
        hits = [s for s in stage.spans[lane] if s[0] <= gs0 <= s[1]]
        assert hits, f"sampled slot maps to no staged episode (lane {lane}, g {gs0})"
        g0, g1, ep = hits[0]
        train_start = gs0 - g0
        assert train_start <= max(0, ep["steps"] - args["forward_steps"])

        if player >= 0:   # ff mode: one target player per window
            monkeypatch.setattr(
                "handyrl_tpu.runtime.batch.random.randrange", lambda _n: player
            )
        host = make_batch([_host_window(ep, train_start, args)], args)

        for key in host:
            host_leaves = jax.tree.leaves(host[key])
            got_leaves = jax.tree.leaves(batch[key])
            assert len(host_leaves) == len(got_leaves), key
            for hl, gl in zip(host_leaves, got_leaves):
                np.testing.assert_allclose(
                    gl[i : i + 1], hl, atol=1e-6, err_msg=f"{key} row {i}"
                )


def test_stage_ff_windows_match_make_batch(monkeypatch):
    """North-star configuration: HungryGeese episodes staged into rings,
    device-assembled ff windows equal make_batch key by key."""
    data = _stage_with_episodes(
        "HungryGeese", n=40, turn_based_training=False, observation=False,
    )
    assert data["stage"].replay.eligible_count() > 0
    _check_windows(data, monkeypatch, n=32)


def test_stage_turn_windows_match_make_batch(monkeypatch):
    """Turn mode (all-player windows + burn-in): TicTacToe episodes with
    observation: true through the same parity bar."""
    data = _stage_with_episodes(
        "TicTacToe", n=16, lanes=2, chunk=8, slots=64,
        turn_based_training=True, observation=True,
        batch_size=4, forward_steps=4, burn_in_steps=2,
    )
    assert data["stage"].replay.eligible_count() > 0
    _check_windows(data, monkeypatch, n=24)


def test_stage_blob_path_matches_decoded_path():
    """add_blob (the wire-codec bytes EpisodeStore mirrors to batcher
    children) must stage bit-identically to add_episode."""
    targs = _targs("TicTacToe", batch_size=4, forward_steps=8,
                   turn_based_training=True, observation=True)
    _, module, eps = _gen_episodes("TicTacToe", 6, targs)
    mesh = make_mesh({"dp": 1})
    stages = []
    for use_blob in (False, True):
        stage = DeviceEpisodeStage(module, targs, mesh, n_lanes=2,
                                   slots=64, chunk_steps=8)
        for ep in eps:
            if use_blob:
                stage.add_blob(codec.dumps(ep))
            else:
                stage.add_episode(ep)
        stage.flush()
        stage.drain()
        stages.append(stage)
    a, b = stages
    assert a.episodes_staged == b.episodes_staged == len(eps)
    assert a.chunks_flushed == b.chunks_flushed > 0
    key = jax.random.PRNGKey(9)
    ba = tree_map(np.asarray, a.replay.sample(key, 8))
    bb = tree_map(np.asarray, b.replay.sample(key, 8))
    for la, lb in zip(jax.tree.leaves(ba), jax.tree.leaves(bb)):
        np.testing.assert_array_equal(la, lb)


def test_stage_lane_balancing_and_spans():
    """Episodes land on the shortest lane; spans are contiguous and
    non-overlapping per lane; staged totals add up."""
    data = _stage_with_episodes(
        "HungryGeese", n=40, turn_based_training=False, observation=False,
    )
    stage = data["stage"]
    assert stage.episodes_staged == len(data["episodes"])
    assert stage.steps_staged == sum(e["steps"] for e in data["episodes"])
    for lane in range(stage.n_lanes):
        pos = 0
        for g0, g1, ep in stage.spans[lane]:
            assert g0 == pos and g1 == pos + ep["steps"] - 1
            pos = g1 + 1
        assert pos == stage._qtotal[lane]
    # greedy balancing: no lane is more than one episode's length ahead
    longest = max(e["steps"] for e in data["episodes"])
    assert max(stage._qtotal) - min(stage._qtotal) <= longest


def test_stage_mode_validation():
    targs = _targs("TicTacToe", turn_based_training=True, observation=False)
    env = make_env({"env": "TicTacToe"})
    mesh = make_mesh({"dp": 1})
    with pytest.raises(ValueError, match="observation"):
        DeviceEpisodeStage(env.net(), targs, mesh)
    targs = _targs("TicTacToe", turn_based_training=False, burn_in_steps=0)
    with pytest.raises(ValueError, match="recurrent"):
        DeviceEpisodeStage(
            make_env({"env": "Geister"}).net(), targs, mesh
        )


def test_device_pipeline_feeds_trainer_batches():
    """The full pipeline surface: store-subscribed episodes upload once,
    batch() returns device-resident dp-sharded batches the train step
    consumes — and the per-stage stats vocabulary stays intact."""
    targs = _targs("HungryGeese", batch_size=4, forward_steps=8,
                   turn_based_training=False, observation=False,
                   device_stage_lanes=2, device_stage_chunk=4,
                   device_stage_slots=256)
    env, module, eps = _gen_episodes("HungryGeese", 8, targs)
    store = EpisodeStore(100)
    mesh = make_mesh({"dp": 1})
    ctx = TrainContext(module, targs, mesh)
    stop = threading.Event()
    pipe = DeviceBatchPipeline(targs, store, ctx, stop)
    store.extend(eps[:4])
    pipe.start()
    store.extend(eps[4:])    # live feed rides the subscription
    try:
        batch = pipe.batch()
        assert batch is not None
        assert isinstance(batch["action"], jax.Array)
        B, T = targs["batch_size"], targs["forward_steps"]
        assert batch["action"].shape[:2] == (B, T)
        # the batch feeds the real train step with no host round-trip
        state = ctx.init_state(init_variables(module, env)["params"])
        state, metrics = ctx.train_step(state, batch, 1e-5)
        assert np.isfinite(float(jax.device_get(metrics["total"])))
        stats = pipe.stats()
        assert stats["mode"] == "device"
        assert stats["batches"] >= 1
        assert stats["episodes_staged"] == len(eps)
        for key in ("sample_s", "assemble_s", "ready_wait_s", "put_s"):
            assert key in stats
    finally:
        stop.set()
        pipe.stop()


def test_make_pipeline_selects_device_mode():
    from handyrl_tpu.runtime.trainer import BatchPipeline, make_pipeline

    targs = _targs("HungryGeese", batch_size=4, forward_steps=8,
                   turn_based_training=False, observation=False,
                   batch_pipeline="device")
    env, module, _ = _gen_episodes("HungryGeese", 1, targs)
    ctx = TrainContext(module, targs, make_mesh({"dp": 1}))
    store = EpisodeStore(10)
    assert isinstance(make_pipeline(targs, store, ctx), DeviceBatchPipeline)
    # a stage-mode misconfiguration falls back LOUDLY instead of dying:
    # recurrent net in ff mode -> shm -> (num_batchers > 0) ShmBatchPipeline
    bad = _targs("Geister", batch_size=4, forward_steps=8,
                 turn_based_training=False, batch_pipeline="device")
    genv = make_env({"env": "Geister"})
    gctx = TrainContext(genv.net(), dict(bad, turn_based_training=True,
                                         observation=True),
                        make_mesh({"dp": 1}))
    pipe = make_pipeline(bad, store, gctx)
    assert not isinstance(pipe, DeviceBatchPipeline)


def test_config_validates_device_stage_knobs():
    with pytest.raises(ValueError, match="device_replay"):
        _targs(batch_pipeline="device", device_replay=True,
               device_rollout_games=8, turn_based_training=False)
    with pytest.raises(ValueError, match="device_stage_slots"):
        _targs(batch_pipeline="device", device_stage_slots=8,
               forward_steps=16, turn_based_training=False)
    with pytest.raises(ValueError, match="device_stage_lanes"):
        _targs(batch_pipeline="device", device_stage_lanes=0,
               turn_based_training=False)
    assert _targs(batch_pipeline="device",
                  turn_based_training=False)["device_stage_chunk"] == 64


@pytest.mark.slow  # full Learner stack; the CI pipeline step still runs it
def test_learner_device_pipeline_end_to_end(tmp_path, monkeypatch):
    """Full --train stack with batch_pipeline: device — device rollouts
    feed HOST episodes into the store, the stage uploads them once, and
    training consumes device-assembled windows: epochs advance,
    checkpoints land, and the metrics record the live 'device' pipeline
    plus the warm-up wait split out of input_wait_frac."""
    import json
    import os

    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    cfg = normalize_args({
        "env_args": {"env": "HungryGeese"},
        "train_args": {
            "turn_based_training": False,
            "observation": False,
            "batch_size": 8,
            "forward_steps": 8,
            "minimum_episodes": 8,
            "update_episodes": 24,
            "maximum_episodes": 1000,
            "epochs": 1,
            "eval_rate": 0.0,
            "device_rollout_games": 8,
            "batch_pipeline": "device",
            "device_stage_lanes": 4,
            "device_stage_chunk": 16,
            "device_stage_slots": 256,
            "mesh": {"dp": 1},
            "worker": {"num_parallel": 1},
        },
    })
    learner = Learner(cfg)
    learner.run()

    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert records, "no metrics were written"
    assert records[-1]["steps"] > 0, "no SGD updates ran"
    assert any(r.get("pipeline") == "device" for r in records)
    trained = [r for r in records if "input_wait_frac" in r]
    assert trained, "no trained epoch recorded input_wait_frac"
    # the run's first batch wait was split out of the starvation metric
    assert any("input_wait_warmup_s" in r for r in trained)
    assert os.path.exists("models/latest.ckpt")
