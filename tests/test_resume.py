"""Checkpoint/resume tests: full train state (params + Adam moments +
step count) round-trips through state.ckpt, and a restarted Learner
continues from it instead of re-warming the optimizer (an improvement
over the reference, which restarts Adam on resume — SURVEY.md §5.4).
"""

import json
import os

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args


def _tiny_args(extra=None):
    return normalize_args(
        {
            "env_args": {"env": "TicTacToe"},
            "train_args": {
                "batch_size": 8,
                "forward_steps": 4,
                "minimum_episodes": 10,
                "update_episodes": 12,
                "maximum_episodes": 100,
                "epochs": 1,
                "num_batchers": 1,
                "eval_rate": 0.2,
                "worker": {"num_parallel": 2},
                **(extra or {}),
            },
        }
    )


def test_train_state_roundtrip(tmp_path):
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime.checkpoint import load_train_state, save_train_state

    args = dict(_tiny_args()["train_args"])
    args["env"] = {"env": "TicTacToe"}
    env = make_env(args["env"])
    module = env.net()
    params = init_variables(module, env)["params"]

    ctx = TrainContext(module, args, make_mesh({"dp": 4, "mp": 2}))
    state = ctx.init_state(params)
    host = jax.device_get(state)
    host["steps"] = np.int32(77)
    path = str(tmp_path / "state.ckpt")
    save_train_state(path, host)

    restored = load_train_state(path, jax.device_get(ctx.init_state(params)))
    assert int(restored["steps"]) == 77
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        host["opt_state"],
        restored["opt_state"],
    )
    # and back onto the mesh with the tensor-parallel layout
    device_state = ctx.put_state(restored)
    kernel_specs = [x.sharding.spec for x in jax.tree.leaves(device_state["params"]) if x.ndim >= 2]
    assert any("mp" in [a for a in spec if a] for spec in kernel_specs)


@pytest.mark.slow
def test_learner_resume_continues_steps(tmp_path, monkeypatch):
    from handyrl_tpu.runtime.learner import Learner

    monkeypatch.chdir(tmp_path)
    learner = Learner(_tiny_args())
    learner.run()
    assert os.path.exists("models/state.ckpt")
    steps_before = learner.trainer.steps
    assert steps_before > 0

    # three more epochs: on a loaded 1-core host the first resumed epoch
    # can complete before the train step finishes recompiling (zero new
    # steps), which is legitimate learner behavior, not a resume bug
    resumed = Learner(_tiny_args({"restart_epoch": 1, "epochs": 4}))
    # the trainer may step a little past the last checkpoint before stopping,
    # so the restored count is positive and at most what we observed live
    assert 0 < resumed.trainer.steps <= steps_before
    resumed.run()
    assert resumed.trainer.steps > steps_before
    records = [json.loads(l) for l in open("metrics.jsonl")]
    assert "input_wait_frac" in records[-1]
    assert "train_steps_per_sec" in records[-1]


@pytest.mark.slow
def test_learner_resume_device_replay(tmp_path, monkeypatch):
    """Resume works in device_replay mode: the rings are ephemeral (they
    refill from fresh self-play) but the train state round-trips — a
    restarted run continues from the checkpointed step count and keeps
    training with zero host episodes."""
    from handyrl_tpu.runtime.learner import Learner

    def _args(extra=None):
        return normalize_args({
            "env_args": {"env": "HungryGeese"},
            "train_args": {
                "turn_based_training": False,
                "observation": False,
                "batch_size": 8,
                "forward_steps": 8,
                "minimum_episodes": 10,
                "update_episodes": 40,
                "maximum_episodes": 1000,
                "epochs": 1,
                "eval_rate": 0.0,
                "device_rollout_games": 8,
                "device_replay": True,
                "device_replay_slots": 256,
                "device_replay_k_steps": 16,
                "worker": {"num_parallel": 1},
                **(extra or {}),
            },
        })

    monkeypatch.chdir(tmp_path)
    learner = Learner(_args())
    learner.run()
    steps_before = learner.trainer.steps
    assert steps_before > 0
    assert learner.trainer.store.total_added == 0

    resumed = Learner(_args({"restart_epoch": 1, "epochs": 3}))
    assert 0 < resumed.trainer.steps <= steps_before
    resumed.run()
    assert resumed.trainer.steps > steps_before
    assert resumed.trainer.store.total_added == 0, (
        "resumed device_replay run must not materialize host episodes"
    )
