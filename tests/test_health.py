"""Cross-host health plane units (parallel/health.py) — socket-free where
possible, one localhost round-trip where the wire itself is the claim.

These are the fast half of the multihost suite: the monitor/watchdog
decision logic runs against an injected clock (no sleeps, no jax, no
subprocesses), so the host-loss detection bounds asserted by the slow
e2es in tests/test_multihost.py are pinned cheaply on every leg.
"""

import socket
import threading
import time

import pytest

from handyrl_tpu.parallel.health import (
    CollectiveWatchdog,
    HostHealthPlane,
    resolve_health_port,
)
from handyrl_tpu.runtime import faults

pytestmark = pytest.mark.multihost


def _plane(on_fault, clock, interval=1.0, timeout=5.0, rank=0, nprocs=3):
    return HostHealthPlane(
        {
            "coordinator_address": "127.0.0.1:6000",
            "heartbeat_interval": interval,
            "heartbeat_timeout": timeout,
        },
        rank,
        nprocs,
        on_fault,
        clock=clock,
    )


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def test_health_port_defaults_to_coordinator_port_plus_one():
    assert resolve_health_port({"coordinator_address": "10.0.0.1:1234"}) == 1235
    assert resolve_health_port(
        {"coordinator_address": "10.0.0.1:1234", "health_port": 7777}
    ) == 7777


def test_peer_silence_counts_misses_then_declares_loss():
    clock = _Clock()
    events = []
    plane = _plane(lambda r, k: events.append((r, k)), clock)
    plane._started_at = clock()
    # both peers beat once at t=100
    plane.last_seen[1] = clock()
    plane.last_seen[2] = clock()
    assert plane.check_peers() is None
    # rank 2 keeps beating, rank 1 goes silent
    clock.t += 2.0
    plane.last_seen[2] = clock()
    assert plane.check_peers() is None  # 2s silence: a miss, not a loss
    assert plane.events["heartbeat_misses"] >= 1
    misses_at_2s = plane.events["heartbeat_misses"]
    clock.t += 1.0
    plane.last_seen[2] = clock()
    plane.check_peers()
    clock.t += 2.5  # rank 1 now 5.5s silent > timeout 5.0
    plane.last_seen[2] = clock()
    assert plane.check_peers() == 1
    assert plane.events["peer_losses"] == 1
    assert 1 in plane.lost
    # one miss per silent interval, not per monitor tick
    assert plane.events["heartbeat_misses"] >= misses_at_2s
    # the healthy peer is never declared lost on later ticks
    clock.t += 0.1
    plane.last_seen[2] = clock()
    assert plane.check_peers() is None


def test_peer_that_never_joined_is_lost_after_grace():
    clock = _Clock()
    plane = _plane(lambda r, k: None, clock, nprocs=2)
    plane._started_at = clock()
    assert plane.check_peers() is None  # inside the join grace
    clock.t += 5.5
    assert plane.check_peers() == 1  # died between jax init and plane start
    assert plane.events["peer_losses"] == 1


def test_fault_callback_fires_at_most_once():
    calls = []
    plane = _plane(lambda r, k: calls.append(k), _Clock())
    plane._fault("a", "peer_loss")
    plane._fault("b", "peer_loss")
    plane._fault("c", "coordinator_loss")
    assert calls == ["peer_loss"]


def test_disarm_silences_both_detectors():
    """After the cadence's agreed stop/drain boundary the trainer disarms
    the plane: teardown is NOT lockstep (worker joins, final fetches skew
    the ranks by seconds), so post-run peer silence must never be declared
    a host fault — an armed plane here os._exit(75)s out of a CLEAN run
    (the first rank to stop answering looks exactly like a lost host)."""
    clock = _Clock()
    calls = []
    plane = _plane(lambda r, k: calls.append(k), clock)
    plane._started_at = clock()
    plane.last_seen[1] = clock()
    plane.last_seen[2] = clock()
    plane.disarm()
    clock.t += 100.0  # both peers silent far past heartbeat_timeout
    rank = plane.check_peers()
    if rank is not None:  # monitor tick still books the silence...
        plane._fault("peer 1 silent", "peer_loss")
    plane._fault("coordinator silent", "coordinator_loss")
    assert calls == []  # ...but no loss can be declared


def test_collective_watchdog_fires_only_past_timeout_and_once():
    clock = _Clock()
    fired = []
    wd = CollectiveWatchdog(10.0, fired.append, clock=clock)
    assert not wd.check()  # never armed
    wd.arm("train_step @ step 7")
    clock.t += 9.0
    assert not wd.check()
    clock.t += 2.0  # 11s armed > 10s timeout
    assert wd.check()
    assert len(fired) == 1 and "train_step @ step 7" in fired[0]
    assert "collective_timeout" in fired[0]
    clock.t += 100.0
    assert wd.check()  # latched, but no second callback
    assert len(fired) == 1
    assert wd.fired


def test_collective_watchdog_disarm_prevents_firing():
    clock = _Clock()
    fired = []
    wd = CollectiveWatchdog(10.0, fired.append, clock=clock)
    wd.arm("x")
    clock.t += 9.9
    wd.disarm()
    clock.t += 100.0
    assert not wd.check()
    assert fired == []
    # zero timeout disables entirely
    wd0 = CollectiveWatchdog(0.0, fired.append, clock=clock)
    wd0.arm("y")
    clock.t += 1e6
    assert not wd0.check()
    assert fired == []


def test_heartbeat_roundtrip_and_loss_echo_over_localhost():
    """One real TCP round-trip: a heartbeat lands in last_seen and the ack
    echoes the coordinator's lost set — the transport under the e2es."""
    import json

    plane = _plane(lambda r, k: None, time.monotonic, interval=0.2, timeout=2.0)
    plane._port = 0  # pick an ephemeral port below
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    plane._server = server
    plane.lost.add(2)  # pre-lost peer must be echoed to survivors

    def serve_one():
        conn, _ = server.accept()
        conn.settimeout(5.0)
        plane._serve_peer(conn)

    t = threading.Thread(target=serve_one, daemon=True)
    t.start()
    port = server.getsockname()[1]
    with socket.create_connection(("127.0.0.1", port), timeout=5.0) as c:
        c.sendall(json.dumps({"rank": 1, "seq": 1}).encode() + b"\n")
        ack = json.loads(c.makefile().readline())
    assert ack["ok"] == 1
    assert ack["lost"] == [2]
    assert 1 in plane.last_seen
    plane.stop()
    server.close()


def test_heartbeat_metrics_piggyback_reaches_the_books():
    """The cross-host metric relay (observability.rank_metrics): a beat
    carrying a ``metrics`` snapshot files it under the sender's rank on
    the coordinator — no second transport, no extra round-trips."""
    import json

    plane = _plane(lambda r, k: None, time.monotonic, interval=0.2, timeout=2.0)
    a, b = socket.socketpair()
    t = threading.Thread(target=plane._serve_peer, args=(b,), daemon=True)
    t.start()
    try:
        a.settimeout(2.0)
        snap = {"epoch": 4, "steps": 120, "train_steps_per_sec": 8.5,
                "input_wait_frac": 0.02}
        a.sendall(json.dumps({"rank": 1, "seq": 1, "metrics": snap}).encode()
                  + b"\n")
        assert b"\n" in a.recv(4096)
        deadline = time.monotonic() + 2.0
        while 1 not in plane.peer_metrics and time.monotonic() < deadline:
            time.sleep(0.01)
        filed, _at = plane.peer_metrics[1]
        assert filed == snap
    finally:
        plane._stop.set()
        a.close()


def test_follower_offer_rides_next_beat_and_survives_a_failed_send():
    """offer_metrics queues newest-wins; a send failure restores the
    snapshot unless a newer one was offered meanwhile."""
    plane = _plane(lambda r, k: None, _Clock(), rank=1)
    plane.offer_metrics({"epoch": 1})
    plane.offer_metrics({"epoch": 2})          # newest wins
    taken = plane._take_pending_metrics()
    assert taken == {"epoch": 2}
    assert plane._take_pending_metrics() is None
    plane._restore_pending_metrics(taken)      # the send failed: keep it
    assert plane._take_pending_metrics() == {"epoch": 2}
    plane._restore_pending_metrics(taken)
    plane.offer_metrics({"epoch": 3})
    plane._restore_pending_metrics({"epoch": 2})  # older loser must NOT clobber
    assert plane._take_pending_metrics() == {"epoch": 3}


def test_rank_aggregates_fold_every_rank():
    clock = _Clock()
    plane = _plane(lambda r, k: None, clock, interval=1.0, nprocs=3)
    plane.note_peer_metrics(1, {"epoch": 3, "steps": 90,
                                "train_steps_per_sec": 10.0,
                                "input_wait_frac": 0.3}, now=clock())
    plane.note_peer_metrics(2, {"epoch": 3, "steps": 90,
                                "train_steps_per_sec": 20.0,
                                "input_wait_frac": 0.1}, now=clock())
    agg = plane.rank_aggregates(
        {"epoch": 3, "steps": 90, "train_steps_per_sec": 30.0,
         "input_wait_frac": 0.2},
    )
    assert agg["rank_reports"] == 3
    assert agg["rank_missing_reports"] == 0
    assert agg["rank_epoch_min"] == agg["rank_epoch_max"] == 3
    assert agg["rank_train_steps_per_sec_min"] == 10.0
    assert agg["rank_train_steps_per_sec_max"] == 30.0
    assert agg["rank_train_steps_per_sec_mean"] == 20.0
    assert agg["rank_input_wait_frac_max"] == 0.3
    assert agg["rank_report_age_s_max"] == 0.0
    assert agg["rank_stale_reports"] == 0


def test_wedged_but_heartbeating_follower_visible_before_watchdog_bound():
    """Acceptance pin (socket-free): a follower whose TRAINER wedges keeps
    heartbeating — the liveness plane sees nothing wrong — but its metric
    snapshot stops advancing, so the coordinator's rank aggregates flag it
    (stale report age, frozen epoch/steps): long before a
    collective_timeout (minutes) fires."""
    clock = _Clock()
    events = []
    plane = _plane(lambda r, k: events.append(k), clock,
                   interval=1.0, timeout=30.0, nprocs=2)
    plane._started_at = clock()
    # three healthy boundaries at a ~1s epoch cadence: beat AND snapshot
    # arrive each time; the aggregation-period EMA learns the cadence
    for epoch in (1, 2, 3):
        plane.last_seen[1] = clock()
        plane.note_peer_metrics(1, {"epoch": epoch, "steps": 30 * epoch,
                                    "train_steps_per_sec": 9.0}, now=clock())
        agg = plane.rank_aggregates({"epoch": epoch, "steps": 30 * epoch,
                                     "train_steps_per_sec": 9.1})
        assert agg["rank_stale_reports"] == 0
        clock.t += 1.0
    # rank 1's trainer wedges; its health thread keeps beating for 10s
    # (well inside heartbeat_timeout 30 and any collective_timeout) but
    # no further snapshot ever arrives
    for _ in range(10):
        clock.t += 1.0
        plane.last_seen[1] = clock()
    assert plane.check_peers() is None          # liveness plane: all good
    assert events == []                          # no fault declared
    # ...but the fold (a later boundary, or the host-fault record) judges
    # rank 1's report against the HEALTHY cadence and flags it
    agg = plane.rank_aggregates({"epoch": 4, "steps": 120,
                                 "train_steps_per_sec": 9.1})
    assert agg["rank_report_age_s_max"] == 11.0
    assert agg["rank_stale_reports"] == 1
    assert agg["rank_epoch_min"] == 3 and agg["rank_epoch_max"] == 4
    assert agg["rank_steps_min"] == 90 and agg["rank_steps_max"] == 120


def test_healthy_long_epochs_are_not_flagged_stale():
    """The inverse pin: snapshots arrive once per EPOCH, so a follower one
    minute-long boundary behind is the healthy steady state — the stale
    bound must track the observed cadence, not the 5s beat interval."""
    clock = _Clock()
    plane = _plane(lambda r, k: None, clock, interval=5.0, nprocs=2)
    for epoch in (1, 2, 3, 4):
        # the fold at boundary N sees the follower's boundary-(N-1)
        # snapshot: one minute old, which is exactly on-cadence
        agg = plane.rank_aggregates({"epoch": epoch, "steps": 10 * epoch})
        assert agg["rank_stale_reports"] == 0, epoch
        if epoch >= 3:  # cadence EMA warmed: the 60s age was judged
            assert agg["rank_report_age_s_max"] == 60.0
        plane.note_peer_metrics(1, {"epoch": epoch, "steps": 10 * epoch},
                                now=clock())
        clock.t += 60.0  # minute-long epochs dwarf 3x heartbeat_interval


def test_wedge_stops_heartbeats_without_teardown():
    plane = _plane(lambda r, k: None, _Clock(), rank=1)
    assert plane._beat.is_set()
    plane.stop_heartbeats()
    assert not plane._beat.is_set()
    assert not plane._stop.is_set()  # the plane itself is still up


def test_wedged_coordinator_stops_acking():
    """HANDYRL_FAULT_WEDGE_PROCESS on rank 0 must make the follower-side
    detector reachable: the coordinator's REAL server half (_serve_peer)
    stops acking once wedged, so followers see their beats unanswered and
    declare coordinator_loss within the bound."""
    import json

    plane = _plane(lambda r, k: None, time.monotonic, interval=0.2, timeout=2.0)
    a, b = socket.socketpair()
    t = threading.Thread(target=plane._serve_peer, args=(b,), daemon=True)
    t.start()
    try:
        a.settimeout(2.0)
        a.sendall(json.dumps({"rank": 1, "seq": 1}).encode() + b"\n")
        assert b"\n" in a.recv(4096)  # healthy: beat is acked
        plane.stop_heartbeats()       # wedge lands on the coordinator
        a.sendall(json.dumps({"rank": 1, "seq": 2}).encode() + b"\n")
        a.settimeout(0.5)
        with pytest.raises(socket.timeout):
            a.recv(4096)              # wedged: beat received, never acked
    finally:
        plane._stop.set()
        a.close()


# -- host-loss fault injection parsing (runtime/faults.py) --------------------


def test_kill_and_wedge_fault_parsing(monkeypatch):
    monkeypatch.delenv("HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH", raising=False)
    monkeypatch.delenv("HANDYRL_FAULT_WEDGE_PROCESS", raising=False)
    assert faults.kill_process_at_epoch() is None
    assert faults.wedge_process_at_epoch() is None
    monkeypatch.setenv("HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH", "2:1")
    assert faults.kill_process_at_epoch() == (2, 1)
    monkeypatch.setenv("HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH", "3")
    assert faults.kill_process_at_epoch() == (3, 0)  # bare epoch = rank 0
    monkeypatch.setenv("HANDYRL_FAULT_WEDGE_PROCESS", "4:2")
    assert faults.wedge_process_at_epoch() == (4, 2)


@pytest.mark.parametrize("raw", ["", ":", "x", "1:x", "1:2:3", "1.5"])
def test_malformed_host_fault_is_loud(monkeypatch, raw):
    if raw == "":
        monkeypatch.setenv("HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH", raw)
        assert faults.kill_process_at_epoch() is None  # unset/blank = off
        return
    monkeypatch.setenv("HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH", raw)
    with pytest.raises(ValueError):
        faults.kill_process_at_epoch()


# -- config validation for the new distributed.* knobs ------------------------


def _cfg(dist):
    from handyrl_tpu.config import normalize_args

    return normalize_args(
        {"env_args": {"env": "TicTacToe"}, "train_args": {"distributed": dist}}
    )


def test_distributed_knob_validation():
    ok = _cfg({"heartbeat_interval": 1.0, "heartbeat_timeout": 5.0})
    assert ok["train_args"]["distributed"]["initialization_timeout"] == 300.0
    with pytest.raises(ValueError, match="initialization_timeout"):
        _cfg({"initialization_timeout": 0})
    with pytest.raises(ValueError, match="heartbeat_timeout"):
        _cfg({"heartbeat_timeout": -1})
    with pytest.raises(ValueError, match="2x"):
        _cfg({"heartbeat_interval": 5.0, "heartbeat_timeout": 6.0})
    with pytest.raises(ValueError, match="collective_timeout"):
        _cfg({"collective_timeout": -1})
    with pytest.raises(ValueError, match="health_port"):
        _cfg({"health_port": 99999})
    with pytest.raises(ValueError, match="num_processes"):
        _cfg({"num_processes": 0})
    # a port-less address must fail as a named knob error at config time,
    # not as a bare int() traceback inside the init pre-flight or
    # resolve_health_port
    with pytest.raises(ValueError, match="coordinator_address"):
        _cfg({"coordinator_address": "10.0.0.1"})
    with pytest.raises(ValueError, match="coordinator_address"):
        _cfg({"coordinator_address": "10.0.0.1:notaport"})
    assert _cfg({"coordinator_address": "10.0.0.1:1234"})
    # coordinator port 65535 is valid, but the DERIVED health port
    # (coordinator port + 1) is not — demand an explicit health_port
    with pytest.raises(ValueError, match="health_port"):
        _cfg({"coordinator_address": "10.0.0.1:65535"})
    with pytest.raises(ValueError, match="health_port"):
        _cfg({"coordinator_address": "10.0.0.1:065535"})  # numeric, not string, compare
    assert _cfg({"coordinator_address": "10.0.0.1:65535", "health_port": 7777})
    # a disabled plane (heartbeat_interval 0) never derives the port
    assert _cfg({"coordinator_address": "10.0.0.1:65535", "heartbeat_interval": 0})


def test_multiprocess_composes_with_device_planes():
    """The PR-6/PR-12 blanket rejections are GONE: the device data plane
    composes with the multi-process cadence (pod-slice rung 1).  The
    exact configs the old rejections refused must now validate."""
    from handyrl_tpu.config import normalize_args

    dist = {"num_processes": 2, "coordinator_address": "127.0.0.1:6000"}
    ok = normalize_args(
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"distributed": dict(dist),
                        "device_rollout_games": 8, "device_replay": True}}
    )
    assert ok["train_args"]["device_replay"] is True
    ok = normalize_args(
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"distributed": dict(dist),
                        "device_rollout_games": 8, "plane": "split"}}
    )
    assert ok["train_args"]["plane"] == "split"
    ok = normalize_args(
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"distributed": dict(dist),
                        "batch_pipeline": "device"}}
    )
    assert ok["train_args"]["batch_pipeline"] == "device"
    # num_processes alone may be a fleet template: without a
    # coordinator_address the plane never activates (init_distributed
    # returns 0), so the same knobs must VALIDATE
    ok = normalize_args(
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"distributed": {"num_processes": 2},
                        "device_rollout_games": 8, "plane": "split"}}
    )
    assert ok["train_args"]["plane"] == "split"


def test_multiprocess_shard_divisibility_validation():
    """What replaced the blanket rejections: the per-process SHARDS must
    divide evenly, and the error names the offending knob."""
    from handyrl_tpu.config import normalize_args

    dist = {"num_processes": 2, "coordinator_address": "127.0.0.1:6000"}
    with pytest.raises(ValueError, match="batch_size"):
        normalize_args(
            {"env_args": {"env": "TicTacToe"},
             "train_args": {"distributed": dict(dist), "batch_size": 7}}
        )
    with pytest.raises(ValueError, match="device_rollout_games"):
        normalize_args(
            {"env_args": {"env": "TicTacToe"},
             "train_args": {"distributed": dict(dist),
                            "device_rollout_games": 7,
                            "device_replay": True}}
        )
    # no coordinator_address = plane never activates: same knobs validate
    assert normalize_args(
        {"env_args": {"env": "TicTacToe"},
         "train_args": {"distributed": {"num_processes": 2},
                        "batch_size": 7}}
    )


def test_pod_slice_knob_validation():
    """distributed.role / plane_port / actor_hosts fail loudly, naming
    the knob (CFG005 keeps these documented in docs/parameters.md)."""
    with pytest.raises(ValueError, match="role"):
        _cfg({"role": "observer"})
    with pytest.raises(ValueError, match="plane_port"):
        _cfg({"plane_port": 99999})
    with pytest.raises(ValueError, match="actor_hosts"):
        _cfg({"actor_hosts": -1})
    # the actor tier hangs off the coordinator host: both ends need the
    # address to derive the gateway endpoint
    with pytest.raises(ValueError, match="coordinator_address"):
        _cfg({"actor_hosts": 1})
    with pytest.raises(ValueError, match="coordinator_address"):
        _cfg({"role": "actor"})
    # a dedicated actor host without the on-device rollout is a no-op
    from handyrl_tpu.config import normalize_args

    with pytest.raises(ValueError, match="device_rollout_games"):
        normalize_args(
            {"env_args": {"env": "TicTacToe"},
             "train_args": {"distributed": {
                 "role": "actor",
                 "coordinator_address": "127.0.0.1:6000"}}}
        )
    # derived plane port overflow: health port 65534 -> plane 65535 is the
    # last valid port; health_port 65534 + 1 = 65535 ok, but a derived
    # 65535 + 1 demands an explicit plane_port
    assert _cfg({"coordinator_address": "10.0.0.1:1234", "actor_hosts": 1,
                 "health_port": 65534})
    with pytest.raises(ValueError, match="plane_port"):
        _cfg({"coordinator_address": "10.0.0.1:1234", "actor_hosts": 1,
              "health_port": 65535})
    assert _cfg({"coordinator_address": "10.0.0.1:1234", "actor_hosts": 1,
                 "health_port": 65535, "plane_port": 7777})
