"""Serving-plane tests (marker: serving): continuous batcher semantics,
multi-model routing, the zero-drop hot-swap pin, and the warm-bucket
compile contract.

The hot-swap test is the subsystem's acceptance pin: clients hammer the
real network server while the router warms + flips a new model, and the
test proves (a) zero dropped requests — every submitted request resolves
with a result, (b) the model-id flip is OBSERVED mid-run in the reply
stream, (c) requests routed to the old id still get the old params.
"""

import threading
import time

import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.runtime.inference_engine import BatchedInferenceEngine, EngineStopped
from handyrl_tpu.serving import (
    ContinuousBatcher,
    DeadlineExceeded,
    ModelRouter,
    RequestShed,
    ServingClient,
    ServingError,
    ServingServer,
)
from handyrl_tpu.utils.sanitizers import RecompileSentinel

pytestmark = pytest.mark.serving


SERVING_CFG = {
    "port": 0,
    "max_models": 3,
    "slo_ms": 2000.0,
    "shed_policy": "none",
    "max_batch": 8,
    "max_wait_ms": 1.0,
    "warm_buckets": [1, 4, 8],
    "queue_bound": 256,
    "recv_timeout": 0.0,
    "watch_interval": 0.0,
    "stats_interval": 0.0,
}


def _tictactoe():
    env = make_env({"env": "TicTacToe"})
    module = env.net()
    env.reset()
    obs = env.observation(0)
    return env, module, obs


def _params(module, env, seed):
    return init_variables(module, env, seed=seed)["params"]


def _batcher(module, params, **overrides):
    import jax

    kwargs = dict(max_batch=8, max_wait_ms=1.0, slo_ms=2000.0,
                  shed_policy="none", queue_bound=256)
    kwargs.update(overrides)
    model = InferenceModel(module, {"params": params})
    return ContinuousBatcher(model, [jax.devices()[0]], **kwargs)


# ---------------------------------------------------------------------------
# continuous batcher
# ---------------------------------------------------------------------------


def test_batcher_matches_direct():
    env, module, obs = _tictactoe()
    params = _params(module, env, 1)
    direct = InferenceModel(module, {"params": params}).inference(obs)
    engine = _batcher(module, params).start()
    futs = [engine.submit(obs) for _ in range(16)]
    for fut in futs:
        out = fut.result(timeout=30)
        np.testing.assert_allclose(out["policy"], direct["policy"], rtol=2e-4, atol=2e-5)
    assert engine.requests_served == 16
    assert engine.batches_served >= 1
    engine.stop()


def test_expired_request_frees_its_slot():
    """Iteration-level scheduling: requests that expire in the queue fail
    with DeadlineExceeded at gather time WITHOUT occupying a device slot —
    the live requests behind them all fit one batch."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1), max_batch=8)
    now = time.monotonic()
    dead = [engine.submit(obs, deadline=now + 0.01) for _ in range(8)]
    live = [engine.submit(obs, deadline=now + 60.0) for _ in range(8)]
    time.sleep(0.05)  # let the short deadlines lapse before the engine runs
    engine.start()
    for fut in dead:
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
    for fut in live:
        assert "policy" in fut.result(timeout=30)
    # 8 expired + 8 live admitted, max_batch 8: the expiries freed their
    # slots inside ONE gather pass, so the live batch went out whole
    assert engine.deadline_misses == 8
    assert engine.requests_served == 8
    assert engine.batches_served == 1
    engine.stop()


def test_admission_controller_sheds_fast():
    """Predicted SLO violation fast-fails at submit — no queue collapse."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1), shed_policy="deadline",
                      slo_ms=10.0)
    # white-box: a measured service rate of 50ms/batch with a batch already
    # in flight makes a 10ms budget unservable
    engine._ema_batch_s = 0.05
    engine._inflight = 1
    fut = engine.submit(obs)
    with pytest.raises(RequestShed):
        fut.result(timeout=5)
    assert engine.requests_shed == 1
    assert engine.requests_admitted == 0
    engine.stop()


def test_idle_engine_admits_despite_poisoned_ema():
    """The estimator recovery valve: a transiently inflated EMA (compile,
    GC pause) must not freeze admission shut — an idle engine serves, the
    batch re-samples the EMA, and admission heals."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1), shed_policy="deadline",
                      slo_ms=50.0).start()
    engine.warm((1,), obs)
    engine._ema_batch_s = 10.0  # 200x the budget: would shed forever
    for _ in range(20):  # idle admits keep serving; each batch re-samples
        assert "policy" in engine.submit(obs).result(timeout=30)
    assert engine.requests_shed == 0
    assert engine._ema_batch_s < 1.0  # the EMA healed (0.8-decay per batch)
    engine.stop()


def test_compile_sample_never_feeds_the_ema():
    """A bucket's first execution is compile-dominated and excluded from
    the service-time EMA (warm() marks its buckets as already paid)."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1)).start()
    assert engine.submit(obs).result(timeout=60)  # first bucket-1 batch
    assert engine._ema_batch_s is None            # compile sample dropped
    assert engine.submit(obs).result(timeout=60)
    assert engine._ema_batch_s is not None        # steady sample counted
    assert engine._ema_batch_s < 1.0
    engine.stop()


def test_queue_bound_sheds():
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1), shed_policy="queue",
                      queue_bound=4)  # not started: the queue only fills
    futs = [engine.submit(obs) for _ in range(5)]
    with pytest.raises(RequestShed):
        futs[-1].result(timeout=5)
    assert engine.requests_shed == 1
    engine.stop()
    for fut in futs[:-1]:  # stop() owns the drain: nothing left pending
        with pytest.raises(EngineStopped):
            fut.result(timeout=5)


def test_malformed_obs_fails_only_its_own_request():
    """A bad observation is rejected at submit (bad_request) and can never
    poison a batch: co-batched valid requests still serve."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1),
                      template_obs=obs).start()
    bad = engine.submit(np.zeros((2, 2), np.float32))  # wrong spec
    good = [engine.submit(obs) for _ in range(4)]
    from handyrl_tpu.serving import BadRequest

    with pytest.raises(BadRequest):
        bad.result(timeout=10)
    for fut in good:
        assert "policy" in fut.result(timeout=30)
    engine.stop()


def test_shed_policy_none_imposes_no_default_deadline():
    """'none' is drain semantics: a request sitting in the queue far past
    slo_ms still completes (only explicit per-request deadlines expire)."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1),
                      shed_policy="none", slo_ms=10.0)  # not started yet
    fut = engine.submit(obs)
    time.sleep(0.1)  # 10x the slo in the queue
    engine.start()
    assert "policy" in fut.result(timeout=30)
    assert engine.deadline_misses == 0
    engine.stop()


def test_cold_resolve_survives_capacity_one(tmp_path):
    """max_models=1: resolving an old snapshot must not have its freshly
    warmed engine retired before the request can submit."""
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    env, module, obs = _tictactoe()
    p1, p5 = _params(module, env, 1), _params(module, env, 5)
    save_epoch_snapshot(str(tmp_path), 1, p1, {"params": p1, "steps": 0}, 0)
    router = ModelRouter(module, obs, dict(SERVING_CFG, max_models=1),
                         model_dir=str(tmp_path))
    router.publish(5, p5)
    served, route = router.resolve(1)  # cold: disk load + warm + spawn
    assert served == 1
    d1 = InferenceModel(module, {"params": p1}).inference(obs)
    out = route.submit(obs).result(timeout=30)  # must not be EngineStopped
    np.testing.assert_allclose(out["policy"], d1["policy"], rtol=2e-4, atol=2e-5)
    assert router.substituted == 0
    router.stop()


def test_concurrent_cold_resolves_pay_one_load(tmp_path):
    """A burst of requests for the same non-resident snapshot spawns ONE
    engine (one disk load, one warm) — the rest wait on the loader."""
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    env, module, obs = _tictactoe()
    p1, p5 = _params(module, env, 1), _params(module, env, 5)
    save_epoch_snapshot(str(tmp_path), 1, p1, {"params": p1, "steps": 0}, 0)
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(5, p5)
    results = [None] * 8

    def resolve(i):
        served, route = router.resolve(1)
        results[i] = (served, route.submit(obs).result(timeout=60))

    threads = [threading.Thread(target=resolve, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    d1 = InferenceModel(module, {"params": p1}).inference(obs)
    for served, out in results:
        assert served == 1
        np.testing.assert_allclose(out["policy"], d1["policy"], rtol=2e-4, atol=2e-5)
    assert router._spawned == 2  # latest + exactly one cold loader
    assert router.substituted == 0
    router.stop()


def test_stopped_router_refuses_cleanly(tmp_path):
    """After stop(), resolve and publish fail with RouteError (never a
    KeyError into the cleared table, never a re-registered leaked engine)."""
    from handyrl_tpu.serving import RouteError

    env, module, obs = _tictactoe()
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    p1 = _params(module, env, 1)
    router.publish(1, p1)
    router.stop()
    with pytest.raises(RouteError, match="stopped"):
        router.resolve(-1)
    with pytest.raises(RouteError, match="stopped"):
        router.publish(2, p1)
    assert router.routes() == []  # the refused publish registered nothing


def test_cold_routes_raise_coldroute_when_disallowed(tmp_path):
    """allow_cold=False is the dispatch thread's contract: anything that
    would pay a disk load / warm compile raises ColdRoute instead."""
    from handyrl_tpu.serving.router import ColdRoute

    env, module, obs = _tictactoe()
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(5, _params(module, env, 1))
    for resident in (-1, 5, 99):  # newer-than-latest serves latest: hot
        assert router.resolve(resident, allow_cold=False)[0] == 5
    with pytest.raises(ColdRoute):
        router.resolve(0, allow_cold=False)   # random route not built yet
    with pytest.raises(ColdRoute):
        router.resolve(3, allow_cold=False)   # would pay disk load + warm
    with pytest.raises(ColdRoute):
        router.resolve([5, 3], allow_cold=False)
    router.resolve(0)                         # cold-build the random route
    assert router.resolve(0, allow_cold=False)[0] == 0  # now hot
    router.stop()


def test_fresh_start_watcher_picks_up_first_epoch(tmp_path):
    """serve_main's cold-start publish (id 0) must not mask training's
    very first verified checkpoint from the manifest watcher."""
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    env, module, obs = _tictactoe()
    p0, p1 = _params(module, env, 1), _params(module, env, 2)
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(0, p0)  # the cold dev server's fresh-init weights
    assert router.maybe_refresh() is None
    save_epoch_snapshot(str(tmp_path), 1, p1, {"params": p1, "steps": 0}, 0)
    assert router.maybe_refresh() == 1
    assert router.latest_id() == 1
    router.stop()


def test_drain_and_stop_completes_admitted_work():
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 1)).start()
    futs = [engine.submit(obs) for _ in range(24)]
    assert engine.drain_and_stop(timeout=60.0)
    for fut in futs:
        assert "policy" in fut.result(timeout=5)  # nothing dropped
    with pytest.raises(EngineStopped):
        engine.submit(obs).result(timeout=5)  # sealed afterwards


# ---------------------------------------------------------------------------
# bucket warm-up: the compile contract (satellite: RecompileSentinel pin)
# ---------------------------------------------------------------------------


def test_engine_compiles_each_bucket_at_most_once():
    """A mixed-size request storm compiles each power-of-two bucket at most
    once; an identical second storm compiles NOTHING."""
    env, module, obs = _tictactoe()
    model = InferenceModel(module, init_variables(module, env, seed=3))
    engine = BatchedInferenceEngine(model, max_batch=8, max_wait_ms=5.0).start()

    def storm():
        futs = []
        for group in (3, 5, 2, 8, 1, 6):
            futs += [engine.submit(obs) for _ in range(group)]
        for fut in futs:
            fut.result(timeout=60)

    with RecompileSentinel() as first:
        storm()
    # buckets are powers of two capped at 8: {1, 2, 4, 8} is every shape
    # the storm can reach, however the engine groups the submissions
    assert first.count <= 4, first.report()
    with RecompileSentinel() as second:
        storm()
    second.assert_no_recompiles("warm mixed-size storm")
    engine.stop()


def test_warm_prepays_every_compile():
    """ContinuousBatcher.warm covers the configured buckets: the post-warm
    storm (what clients see right after a hot-swap flip) is compile-free."""
    env, module, obs = _tictactoe()
    engine = _batcher(module, _params(module, env, 4)).start()
    engine.warm((1, 2, 4, 8), obs)
    with RecompileSentinel() as sentinel:
        futs = [engine.submit(obs) for _ in range(13)]
        for fut in futs:
            fut.result(timeout=60)
    sentinel.assert_no_recompiles("post-warm storm")
    engine.stop()


# ---------------------------------------------------------------------------
# router: multi-model, ensemble, substitution accounting
# ---------------------------------------------------------------------------


def test_router_routes_by_model_id(tmp_path):
    env, module, obs = _tictactoe()
    p1, p2 = _params(module, env, 1), _params(module, env, 2)
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(1, p1)
    router.publish(2, p2)
    assert router.latest_id() == 2
    assert router.routes() == [1, 2]

    d1 = InferenceModel(module, {"params": p1}).inference(obs)
    d2 = InferenceModel(module, {"params": p2}).inference(obs)
    for mid, want in ((-1, d2), (2, d2), (1, d1), (99, d2)):
        served, route = router.resolve(mid)
        out = route.submit(obs).result(timeout=30)
        np.testing.assert_allclose(out["policy"], want["policy"], rtol=2e-4, atol=2e-5)
        assert served == (2 if mid != 1 else 1)
    router.stop()


def test_router_ensemble_mean_pools(tmp_path):
    env, module, obs = _tictactoe()
    p1, p2 = _params(module, env, 1), _params(module, env, 2)
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(1, p1)
    router.publish(2, p2)
    d1 = InferenceModel(module, {"params": p1}).inference(obs)
    d2 = InferenceModel(module, {"params": p2}).inference(obs)
    served, route = router.resolve([1, 2])
    out = route.submit(obs).result(timeout=30)
    assert served == (1, 2)
    np.testing.assert_allclose(
        out["policy"],
        (np.asarray(d1["policy"], np.float32) + np.asarray(d2["policy"], np.float32)) / 2.0,
        rtol=2e-4, atol=2e-5,
    )
    router.stop()


def test_ensemble_refuses_hidden_state(tmp_path):
    """An ensemble route cannot thread per-member recurrent state: a
    hidden-carrying request is refused loudly, never silently served from
    initial state."""
    from handyrl_tpu.serving import BadRequest

    env, module, obs = _tictactoe()
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(1, _params(module, env, 1))
    router.publish(2, _params(module, env, 2))
    _served, route = router.resolve([1, 2])
    with pytest.raises(BadRequest, match="recurrent"):
        route.submit(obs, hidden={"h": np.zeros(4)}).result(timeout=10)
    router.stop()


def test_router_substitution_is_counted(tmp_path):
    """A requested snapshot that cannot be verified serves latest AND
    increments the substitution counter — never a silent swap."""
    env, module, obs = _tictactoe()
    router = ModelRouter(module, obs, SERVING_CFG, model_dir=str(tmp_path))
    router.publish(5, _params(module, env, 1))
    served, _route = router.resolve(3)  # 3.ckpt does not exist
    assert served == 5
    assert router.substituted == 1
    assert router.stats()["substituted"] == 1
    router.stop()


def test_local_model_server_substitution_is_counted(tmp_path):
    """Satellite pin: LocalModelServer's substitute-latest fallback is a
    visible cumulative counter, surfaced as serve_snapshot_substituted."""
    from handyrl_tpu.runtime.worker import LocalModelServer

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    server = LocalModelServer(
        module, env, {"model_dir": str(tmp_path), "inference_batch_size": 8}
    )
    server.publish(5, init_variables(module, env, seed=1)["params"])
    assert server.substituted_snapshots == 0
    client = server.get(3)  # snapshot 3 was never written: substitutes latest
    assert client is not None
    assert server.substituted_snapshots == 1
    server.get(2)
    assert server.substituted_snapshots == 2
    server.engine.stop()


def test_router_eviction_drains_not_drops(tmp_path):
    env, module, obs = _tictactoe()
    cfg = dict(SERVING_CFG, max_models=2)
    router = ModelRouter(module, obs, cfg, model_dir=str(tmp_path))
    engines = {}
    for mid in (1, 2, 3):
        router.publish(mid, _params(module, env, mid))
        if mid == 1:  # traffic the eviction must not erase from the books
            assert "policy" in router.resolve(1)[1].submit(obs).result(timeout=30)
        engines[mid] = router._engines.get(mid)
    # capacity 2: model 1 (LRU non-latest) was evicted, latest pinned
    assert router.latest_id() == 3
    assert 3 in router.routes() and len(router.routes()) == 2
    for t in list(router._retiring):
        t.join(30)
    evicted = engines[1]
    assert evicted is not None and evicted._stop.is_set()
    # cumulative stats stay monotonic across the eviction: the retired
    # engine's served count folded into the router totals
    assert router.stats()["requests_served"] >= 1
    router.stop()


# ---------------------------------------------------------------------------
# the network server + the hot-swap acceptance pin
# ---------------------------------------------------------------------------


def _start_server(module, obs, tmp_path, **cfg_overrides):
    cfg = dict(SERVING_CFG, **cfg_overrides)
    router = ModelRouter(module, obs, cfg, model_dir=str(tmp_path))
    server = ServingServer(router, cfg).run()
    return router, server


def test_server_roundtrip_and_stats(tmp_path):
    env, module, obs = _tictactoe()
    p1 = _params(module, env, 1)
    router, server = _start_server(module, obs, tmp_path)
    router.publish(1, p1)
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        direct = InferenceModel(module, {"params": p1}).inference(obs)
        reply = client.infer(obs)
        assert reply["model"] == 1
        np.testing.assert_allclose(
            reply["out"]["policy"], direct["policy"], rtol=2e-4, atol=2e-5
        )
        ens = client.infer(obs, model=[1, 1])
        assert tuple(ens["model"]) == (1, 1)
        rnd = client.infer(obs, model=0)
        assert rnd["model"] == 0
        assert float(np.abs(np.asarray(rnd["out"]["policy"])).sum()) == 0.0
        stats = client.stats()
        assert stats["serve_replies"] >= 3
        assert stats["serve_models"] == 1
        assert stats["serve_p50_ms"] is not None
        assert stats["serve_snapshot_substituted"] == 0
    finally:
        client.close()
        server.shutdown()


def test_server_reports_shed_over_the_wire(tmp_path):
    env, module, obs = _tictactoe()
    router, server = _start_server(
        module, obs, tmp_path, shed_policy="deadline", slo_ms=50.0
    )
    router.publish(1, _params(module, env, 1))
    # force an unservable prediction on the one resident engine
    engine = router._engines[1]
    engine._ema_batch_s = 10.0
    engine._inflight = 1
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        with pytest.raises(ServingError) as err:
            client.infer(obs, slo_ms=5.0)
        assert err.value.kind in ("shed", "deadline")
        assert client.stats()["serve_shed"] >= 1
    finally:
        client.close()
        server.shutdown()


def test_hot_swap_under_load_drops_nothing(tmp_path):
    """THE acceptance pin: hammer the server across a hot-swap; every
    request is answered, the flip is observed mid-run, nothing drops."""
    env, module, obs = _tictactoe()
    p1, p2 = _params(module, env, 1), _params(module, env, 2)
    router, server = _start_server(module, obs, tmp_path, shed_policy="none")
    router.publish(1, p1)

    stop = threading.Event()
    lock = threading.Lock()
    served_ids = []
    submitted = [0]
    failures = []

    def hammer():
        client = ServingClient("127.0.0.1", server.bound_port)
        try:
            while not stop.is_set():
                with lock:
                    submitted[0] += 1
                try:
                    reply = client.infer(obs, timeout=60)
                    with lock:
                        served_ids.append(reply["model"])
                except Exception as exc:  # any failure = a dropped request
                    with lock:
                        failures.append(repr(exc))
                    return
        finally:
            client.close()

    threads = [threading.Thread(target=hammer, daemon=True) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.4)  # steady-state load on model 1

    admin = ServingClient("127.0.0.1", server.bound_port)
    swap = admin.swap(2, params=p2)
    assert swap["id"] == 2
    assert swap["warm_ms"] > 0  # the standby engine really warmed pre-flip

    time.sleep(0.4)  # steady-state load on model 2
    stop.set()
    for t in threads:
        t.join(30)
    admin.close()
    server.shutdown()

    assert not failures, failures[:5]
    assert len(served_ids) == submitted[0]  # zero dropped requests
    assert set(served_ids) == {1, 2}        # the flip observed mid-run
    # load started well before the swap and ran well past it: the stream
    # begins on the old model and ends on the new one
    assert served_ids[0] == 1 and served_ids[-1] == 2


def test_cold_model_served_over_the_wire(tmp_path):
    """A request for a non-resident snapshot takes the cold pool path
    (ColdRoute) and still serves — off the dispatch thread."""
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    env, module, obs = _tictactoe()
    p1, p5 = _params(module, env, 1), _params(module, env, 5)
    save_epoch_snapshot(str(tmp_path), 1, p1, {"params": p1, "steps": 0}, 0)
    router, server = _start_server(module, obs, tmp_path)
    router.publish(5, p5)
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        reply = client.infer(obs, model=1, timeout=120)
        assert reply["model"] == 1
        d1 = InferenceModel(module, {"params": p1}).inference(obs)
        np.testing.assert_allclose(
            reply["out"]["policy"], d1["policy"], rtol=2e-4, atol=2e-5
        )
    finally:
        client.close()
        server.shutdown()


def test_malformed_frames_do_not_kill_the_dispatch_thread(tmp_path):
    """One bad frame (None payload, junk slo_ms, unknown request, bad obs)
    must error THAT request only — the server keeps serving everyone."""
    from handyrl_tpu.runtime.connection import connect_socket_connection

    env, module, obs = _tictactoe()
    router, server = _start_server(module, obs, tmp_path)
    router.publish(1, _params(module, env, 1))
    raw = connect_socket_connection("127.0.0.1", server.bound_port)
    try:
        raw.send(("infer", None))                      # payload not a dict
        raw.send(("infer", {"rid": 2, "obs": obs, "slo_ms": "soon"}))
        raw.send(("infer", {"rid": 3, "obs": None}))   # spec-violating obs
        raw.send(("no_such_request", {"rid": 4}))
        kinds = {}
        for _ in range(4):
            kind, data = raw.recv(timeout=30)
            assert kind == "error"
            kinds[data.get("rid")] = data["kind"]
        assert kinds[2] == "bad_request"               # junk slo_ms
        assert kinds[3] == "bad_request"               # obs spec gate
        assert kinds[4] == "bad_request"               # unknown request
    finally:
        raw.close()
    # the dispatch thread survived all of it: a clean client still serves
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        assert client.infer(obs, timeout=30)["model"] == 1
    finally:
        client.close()
        server.shutdown()


def test_swap_from_disk_verified(tmp_path):
    """swap with no inline params loads the digest-verified snapshot."""
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    env, module, obs = _tictactoe()
    p1, p2 = _params(module, env, 1), _params(module, env, 2)
    save_epoch_snapshot(str(tmp_path), 7, p2, {"params": p2, "steps": 0}, 0)
    router, server = _start_server(module, obs, tmp_path)
    router.publish(1, p1)
    client = ServingClient("127.0.0.1", server.bound_port)
    try:
        swap = client.swap(7)
        assert swap["id"] == 7
        d2 = InferenceModel(module, {"params": p2}).inference(obs)
        reply = client.infer(obs)
        assert reply["model"] == 7
        np.testing.assert_allclose(
            reply["out"]["policy"], d2["policy"], rtol=2e-4, atol=2e-5
        )
    finally:
        client.close()
        server.shutdown()


def test_watcher_hot_swaps_on_new_verified_snapshot(tmp_path):
    from handyrl_tpu.runtime.checkpoint import save_epoch_snapshot

    env, module, obs = _tictactoe()
    p1, p2 = _params(module, env, 1), _params(module, env, 2)
    router, server = _start_server(module, obs, tmp_path, watch_interval=0.1)
    router.publish(1, p1)
    save_epoch_snapshot(str(tmp_path), 9, p2, {"params": p2, "steps": 0}, 0)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and router.latest_id() != 9:
        time.sleep(0.05)
    assert router.latest_id() == 9
    server.shutdown()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def _cfg(**serving):
    return {"env_args": {"env": "TicTacToe"}, "train_args": {"serving": serving}}


def test_serving_config_validation():
    assert normalize_args(_cfg())  # defaults valid
    with pytest.raises(ValueError, match="shed_policy"):
        normalize_args(_cfg(shed_policy="panic"))
    with pytest.raises(ValueError, match="warm_buckets"):
        normalize_args(_cfg(warm_buckets=[3]))
    with pytest.raises(ValueError, match="exceeds"):
        normalize_args(_cfg(warm_buckets=[128], max_batch=64))
    with pytest.raises(ValueError, match="slo_ms"):
        normalize_args(_cfg(slo_ms=0))
    with pytest.raises(ValueError, match="max_models"):
        normalize_args(_cfg(max_models=0))
    with pytest.raises(ValueError, match="port"):
        normalize_args(_cfg(port=70000))
    with pytest.raises(ValueError, match="watch_interval"):
        normalize_args(_cfg(watch_interval=-1))
