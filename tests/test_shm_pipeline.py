"""Shared-memory process-batcher pipeline + C columnar-fill parity tests.

The contract under test (ISSUE 1 acceptance): batches produced by the
GIL-free assembly plane — batcher processes filling shared-memory ring
slots, with the C fill kernels — are BIT-IDENTICAL to the in-thread numpy
``make_batch`` reference, on CPU, and the plane shuts down cleanly (no
orphaned processes, no leaked shm segments).

Every test here is fast (seconds) and CPU-only; the CI workflow runs this
module standalone as the process-batcher smoke path (``-m pipeline``).
"""

import random
import threading
import time
from multiprocessing import shared_memory

import jax
import numpy as np
import pytest

from handyrl_tpu.config import normalize_args
from handyrl_tpu.envs import make_env
from handyrl_tpu.models import InferenceModel, init_variables
from handyrl_tpu.runtime import batch as batch_mod
from handyrl_tpu.runtime.batch import fill_batch, make_batch
from handyrl_tpu.runtime.generation import Generator
from handyrl_tpu.runtime.replay import EpisodeStore
from handyrl_tpu.runtime.shm_batch import ShmBatchPipeline, slot_spec, slot_views
from handyrl_tpu.runtime.trainer import BatchPipeline, make_pipeline

pytestmark = pytest.mark.pipeline


def _targs(env="TicTacToe", **over):
    raw = {"env_args": {"env": env}, "train_args": over}
    return normalize_args(raw)["train_args"]


def _gen_store(env_name, n, targs, seed=0):
    random.seed(seed)
    env = make_env({"env": env_name})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env, seed=seed))
    gen = Generator(env, targs)
    models = {p: model for p in env.players()}
    gen_args = {"player": env.players(), "model_id": {p: 1 for p in env.players()}}
    store = EpisodeStore(1000)
    eps = []
    while len(eps) < n:
        ep = gen.generate(models, gen_args)
        if ep is not None:
            eps.append(ep)
    store.extend(eps)
    return store, eps


def _assert_batches_identical(ref, got):
    assert set(ref) == set(got)
    for key in ref:
        ref_leaves = jax.tree.leaves(ref[key])
        got_leaves = jax.tree.leaves(got[key])
        assert len(ref_leaves) == len(got_leaves), key
        for rl, gl in zip(ref_leaves, got_leaves):
            assert rl.dtype == gl.dtype, key
            assert rl.shape == gl.shape, key
            assert rl.tobytes() == gl.tobytes(), f"{key}: bytes differ"


class _HostCtx:
    """put_batch stub: deep-copies, so recycled slots can never alias the
    'device' batch (mirrors what a real H2D transfer guarantees)."""

    def put_batch(self, batch):
        return jax.tree.map(np.array, batch)

    def put_batches(self, batches):
        return [jax.tree.map(np.array, b) for b in batches]


# -- C fill kernels vs numpy reference --------------------------------------


def test_c_fill_path_bit_identical_to_numpy():
    """Same windows through the C fill kernels and the pure-numpy fill
    must produce byte-for-byte identical batches (turn-based gather)."""
    targs = _targs(batch_size=8, forward_steps=8, burn_in_steps=2)
    store, _ = _gen_store("TicTacToe", 10, targs)
    windows = [store.sample_window(8, 2, 4) for _ in range(8)]
    if batch_mod._ACCEL is None:
        pytest.skip("C accelerator unavailable (no compiler?)")
    accel = batch_mod._ACCEL
    try:
        got = make_batch(windows, targs)
        batch_mod._ACCEL = None
        ref = make_batch(windows, targs)
    finally:
        batch_mod._ACCEL = accel
    _assert_batches_identical(ref, got)


def test_c_fill_path_bit_identical_simultaneous_env():
    """Simultaneous-move path (HungryGeese: 4 players/step, big obs)."""
    targs = _targs("HungryGeese", batch_size=4, forward_steps=8)
    store, _ = _gen_store("HungryGeese", 4, targs)
    windows = [store.sample_window(8, 0, 4) for _ in range(4)]
    if batch_mod._ACCEL is None:
        pytest.skip("C accelerator unavailable (no compiler?)")
    accel = batch_mod._ACCEL
    try:
        got = make_batch(windows, targs)
        batch_mod._ACCEL = None
        ref = make_batch(windows, targs)
    finally:
        batch_mod._ACCEL = accel
    _assert_batches_identical(ref, got)


def test_fill_kernels_validate_bounds():
    if batch_mod._ACCEL is None:
        pytest.skip("C accelerator unavailable (no compiler?)")
    acc = batch_mod._ACCEL
    dst = np.zeros((2, 4, 3), np.float32)
    src = np.ones((3, 3), np.float32)
    with pytest.raises(ValueError):
        acc.fill_column(dst, [0, 0, 0], [src, src, src])  # more windows than B
    with pytest.raises(ValueError):
        acc.fill_column(dst, [0, 2], [src, src])  # second window overruns T
    with pytest.raises(ValueError):
        acc.fill_column(dst, [0], [np.ones((3, 4), np.float32)])  # row shape
    with pytest.raises(ValueError):
        acc.fill_rows(dst, 0, 0, 5, np.ones((3,), np.float32))  # hi > T
    with pytest.raises(ValueError):
        acc.fill_rows(dst, 2, 0, 4, np.ones((3,), np.float32))  # b out of range
    # and valid calls round-trip
    acc.fill_column(dst, [1, 0], [src[:2], src])
    assert np.array_equal(dst[0, 1:3], src[:2])
    assert np.array_equal(dst[1, 0:3], src)
    row = np.full((3,), 7.0, np.float32)
    acc.fill_rows(dst, 0, 3, 4, row)
    assert np.array_equal(dst[0, 3], row)


# -- shared-memory slot fill -------------------------------------------------


def test_fill_batch_into_dirty_shm_slot_bit_identical():
    """fill_batch into a reused (garbage-filled) shm slot must equal the
    freshly allocated make_batch reference — proves the per-slot reset
    restores every padding default."""
    targs = _targs(batch_size=6, forward_steps=8)
    store, _ = _gen_store("TicTacToe", 8, targs)
    windows = [store.sample_window(8, 0, 4) for _ in range(6)]
    ref = make_batch(windows, targs)
    spec, slot_bytes = slot_spec(ref)
    shm = shared_memory.SharedMemory(create=True, size=slot_bytes)
    try:
        views = slot_views(spec, shm.buf, 0)
        shm.buf[:slot_bytes] = bytes([0xAB]) * slot_bytes  # dirty slot
        fill_batch(windows, targs, views)
        _assert_batches_identical(ref, views)
        # second fill over its own previous content (the steady state)
        fill_batch(windows, targs, views)
        _assert_batches_identical(ref, views)
    finally:
        views = None
        import gc

        gc.collect()
        shm.close()
        shm.unlink()


# -- the full process pipeline ----------------------------------------------


def test_process_batcher_batch_matches_make_batch_bit_identical():
    """Cross-process parity: with ONE short episode and forward_steps >
    episode length, window sampling is deterministic (train_start 0, whole
    episode), so a batch assembled by a batcher process in shared memory
    must be bit-identical to make_batch in this process."""
    targs = _targs(batch_size=2, forward_steps=16, num_batchers=1)
    store, eps = _gen_store("TicTacToe", 1, targs)
    assert eps[0]["steps"] <= 16
    windows = [store.sample_window(16, 0, 4) for _ in range(2)]
    ref = make_batch(windows, targs)

    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    try:
        assert pipe._fallback is None, "shm plane fell back to threads"
        got = pipe.batch()
        assert got is not None
        _assert_batches_identical(ref, got)
    finally:
        stop.set()
        pipe.stop()


def test_process_pipeline_produces_and_cleans_up():
    targs = _targs(batch_size=4, forward_steps=8, num_batchers=2)
    store, eps = _gen_store("TicTacToe", 8, targs)
    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    assert pipe._fallback is None
    shm_name = pipe._shm.name
    ref_shape = make_batch([store.sample_window(8, 0, 4) for _ in range(4)], targs)
    for _ in range(3):
        got = pipe.batch()
        assert got is not None
        assert got["observation"].shape == ref_shape["observation"].shape
        assert got["action"].dtype == np.int32
        assert float(got["episode_mask"].sum()) > 0
    # live episode feed must not disturb the stream
    store.extend(eps[:2])
    assert pipe.batch() is not None
    stats = pipe.stats()
    assert stats["mode"] == "shm"
    assert stats["batches"] >= 4
    assert stats["assemble_s"] > 0
    pipe.stop()
    for proc in pipe._procs:
        assert not proc.is_alive(), "orphaned batcher process"
    with pytest.raises(FileNotFoundError):
        shared_memory.SharedMemory(name=shm_name)


def test_stop_event_alone_reaps_processes_and_shm():
    """Trainer-style shutdown: ONLY the shared stop_event is set; the
    pipeline's own threads must join the children and unlink the segment
    (the no-orphaned-shm acceptance criterion)."""
    targs = _targs(batch_size=4, forward_steps=8, num_batchers=2)
    store, _ = _gen_store("TicTacToe", 6, targs)
    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    assert pipe._fallback is None
    shm_name = pipe._shm.name
    assert pipe.batch() is not None
    stop.set()
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            probe = shared_memory.SharedMemory(name=shm_name)
            probe.close()
            time.sleep(0.2)
        except FileNotFoundError:
            break
    else:
        pytest.fail("shm segment still linked 15s after stop_event")
    for proc in pipe._procs:
        proc.join(timeout=5)
        assert not proc.is_alive()


def test_fused_grouping_through_shm_pipeline():
    targs = _targs(batch_size=4, forward_steps=8, num_batchers=1, fused_steps=2)
    store, _ = _gen_store("TicTacToe", 6, targs)
    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    try:
        assert pipe._fallback is None
        group = pipe.batch()
        assert isinstance(group, list) and len(group) == 2
    finally:
        stop.set()
        pipe.stop()


# -- multi-batcher slot accounting (ISSUE 6) ---------------------------------


@pytest.mark.parametrize("nb", [2, 4])
def test_multi_batcher_slot_accounting(nb):
    """The shm plane at 2 and 4 children: every ring slot is dealt AND
    consumed, recycled through the generation counter, and no (slot,
    generation) pair ever circulates twice — the invariant that makes a
    reclaimed slot's stale ready message self-invalidating."""
    targs = _targs(batch_size=4, forward_steps=8, num_batchers=nb,
                   shm_slots=5)
    store, _ = _gen_store("TicTacToe", 8, targs)
    stop = threading.Event()
    pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
    seen = []
    orig = pipe._ready_get

    def spy():
        item = orig()
        if item is not None:
            # generation at consume time == generation stamped at fill
            # time (the recycle bump happens strictly after this return)
            seen.append((item[0], int(pipe._slot_gen[item[0]])))
        return item

    pipe._ready_get = spy
    pipe.start()
    try:
        assert pipe._fallback is None, "shm plane fell back to threads"
        n_slots = pipe._n_slots
        for _ in range(3 * n_slots):
            assert pipe.batch() is not None
        assert len(seen) >= 3 * n_slots
        # a (slot, generation) consumed twice = a slot circulating twice
        assert len(set(seen)) == len(seen), "a slot generation was consumed twice"
        # every slot of the ring was dealt to a child and flowed through
        assert {s for s, _ in seen} == set(range(n_slots))
        # every child held work (round-robin dealing reaches all children)
        deaths = pipe.stats()["batcher_deaths"]
        assert deaths == 0
    finally:
        stop.set()
        pipe.stop()


@pytest.mark.slow  # three pipeline spawns; the CI pipeline step still runs it
def test_multi_batcher_parity_with_single_child():
    """Deterministic single-short-episode setup (window sampling collapses
    to train_start 0, whole episode): batches from 2- and 4-child rings
    must be bit-identical to the 1-child configuration's, which is itself
    pinned to make_batch."""
    base = _targs(batch_size=2, forward_steps=16, num_batchers=1)
    store, eps = _gen_store("TicTacToe", 1, base)
    assert eps[0]["steps"] <= 16
    windows = [store.sample_window(16, 0, 4) for _ in range(2)]
    ref = make_batch(windows, base)
    for nb in (1, 2, 4):
        targs = dict(base, num_batchers=nb)
        stop = threading.Event()
        pipe = ShmBatchPipeline(targs, store, _HostCtx(), stop)
        pipe.start()
        try:
            assert pipe._fallback is None
            got = pipe.batch()
            assert got is not None
            _assert_batches_identical(ref, got)
        finally:
            stop.set()
            pipe.stop()


# -- factory + config wiring -------------------------------------------------


def test_make_pipeline_mode_selection():
    targs = _targs(batch_size=4, forward_steps=8, num_batchers=1)
    store = EpisodeStore(10)
    ctx = _HostCtx()
    assert isinstance(make_pipeline(targs, store, ctx), ShmBatchPipeline)
    thread_args = dict(targs, batch_pipeline="thread")
    assert isinstance(make_pipeline(thread_args, store, ctx), BatchPipeline)
    no_batchers = dict(targs, num_batchers=0)
    assert isinstance(make_pipeline(no_batchers, store, ctx), BatchPipeline)


def test_config_validates_pipeline_knobs():
    with pytest.raises(ValueError):
        _targs(batch_pipeline="fiber")
    with pytest.raises(ValueError):
        _targs(shm_slots=1)
    # loud at startup, not deep in shm_batch setup (ISSUE 6 satellite):
    # a negative batcher count, or more children than ring slots (a child
    # beyond the ring depth would never hold a slot)
    with pytest.raises(ValueError):
        _targs(num_batchers=-1)
    with pytest.raises(ValueError):
        _targs(num_batchers=9, shm_slots=6)
    assert _targs(num_batchers=0)["num_batchers"] == 0  # 0 = threaded
    assert _targs()["batch_pipeline"] == "shm"


def test_thread_pipeline_reports_stage_stats():
    targs = _targs(batch_size=4, forward_steps=8, num_batchers=1,
                   batch_pipeline="thread")
    store, _ = _gen_store("TicTacToe", 6, targs)
    stop = threading.Event()
    pipe = BatchPipeline(targs, store, _HostCtx(), stop)
    pipe.start()
    try:
        assert pipe.batch() is not None
        stats = pipe.stats()
        assert stats["mode"] == "thread"
        assert stats["batches"] >= 1
        for key in ("sample_s", "assemble_s", "free_wait_s", "ready_wait_s", "put_s"):
            assert key in stats
    finally:
        stop.set()
        pipe.stop()
