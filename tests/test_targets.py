"""Golden tests for RL target algorithms.

Each algorithm is re-derived here as a naive per-timestep numpy loop
straight from the formulas (TD(lambda) backup, UPGO max-bootstrap, V-Trace
per arXiv:1802.01561) and compared against the lax.scan implementations.
"""

import numpy as np
import pytest

from handyrl_tpu.ops.targets import compute_target

B, T, P, C = 2, 5, 2, 1
RNG = np.random.default_rng(0)


def _rand():
    values = RNG.normal(size=(B, T, P, C)).astype(np.float32)
    returns = RNG.normal(size=(B, T, P, C)).astype(np.float32)
    rewards = RNG.normal(size=(B, T, P, C)).astype(np.float32)
    rhos = RNG.uniform(0.2, 1.0, size=(B, T, P, C)).astype(np.float32)
    cs = RNG.uniform(0.2, 1.0, size=(B, T, P, C)).astype(np.float32)
    masks = (RNG.uniform(size=(B, T, P, C)) > 0.3).astype(np.float32)
    return values, returns, rewards, rhos, cs, masks


def _naive_td(values, returns, rewards, lam, gamma):
    tgt = np.zeros_like(values)
    tgt[:, -1] = returns[:, -1]
    for i in range(T - 2, -1, -1):
        r = rewards[:, i] if rewards is not None else 0
        l1 = lam[:, i + 1]
        tgt[:, i] = r + gamma * ((1 - l1) * values[:, i + 1] + l1 * tgt[:, i + 1])
    return tgt


def _naive_upgo(values, returns, rewards, lam, gamma):
    tgt = np.zeros_like(values)
    tgt[:, -1] = returns[:, -1]
    for i in range(T - 2, -1, -1):
        r = rewards[:, i] if rewards is not None else 0
        l1 = lam[:, i + 1]
        v1 = values[:, i + 1]
        tgt[:, i] = r + gamma * np.maximum(v1, (1 - l1) * v1 + l1 * tgt[:, i + 1])
    return tgt


def _naive_vtrace(values, returns, rewards, lam, gamma, rhos, cs):
    r = rewards if rewards is not None else np.zeros_like(values)
    v_next = np.concatenate([values[:, 1:], returns[:, -1:]], axis=1)
    deltas = rhos * (r + gamma * v_next - values)
    x = np.zeros_like(values)
    x[:, -1] = deltas[:, -1]
    for i in range(T - 2, -1, -1):
        x[:, i] = deltas[:, i] + gamma * lam[:, i + 1] * cs[:, i] * x[:, i + 1]
    vs = x + values
    vs_next = np.concatenate([vs[:, 1:], returns[:, -1:]], axis=1)
    adv = r + gamma * vs_next - values
    return vs, adv


@pytest.mark.parametrize("gamma", [1.0, 0.9])
@pytest.mark.parametrize("lmb", [0.7, 1.0])
@pytest.mark.parametrize("with_rewards", [True, False])
def test_td_lambda(gamma, lmb, with_rewards):
    values, returns, rewards, rhos, cs, masks = _rand()
    rewards = rewards if with_rewards else None
    tgt, adv = compute_target("TD", values, returns, rewards, lmb, gamma, rhos, cs, masks)
    lam = lmb + (1 - lmb) * (1 - masks)
    expect = _naive_td(values, returns, rewards, lam, gamma)
    np.testing.assert_allclose(tgt, expect, rtol=1e-5)
    np.testing.assert_allclose(adv, expect - values, rtol=1e-5)


@pytest.mark.parametrize("gamma", [1.0, 0.8])
def test_upgo(gamma):
    values, returns, rewards, rhos, cs, masks = _rand()
    tgt, adv = compute_target("UPGO", values, returns, rewards, 0.7, gamma, rhos, cs, masks)
    lam = 0.7 + 0.3 * (1 - masks)
    expect = _naive_upgo(values, returns, rewards, lam, gamma)
    np.testing.assert_allclose(tgt, expect, rtol=1e-5)
    np.testing.assert_allclose(adv, expect - values, rtol=1e-5)


@pytest.mark.parametrize("gamma", [1.0, 0.8])
@pytest.mark.parametrize("with_rewards", [True, False])
def test_vtrace(gamma, with_rewards):
    values, returns, rewards, rhos, cs, masks = _rand()
    rewards = rewards if with_rewards else None
    tgt, adv = compute_target("VTRACE", values, returns, rewards, 0.7, gamma, rhos, cs, masks)
    lam = 0.7 + 0.3 * (1 - masks)
    e_tgt, e_adv = _naive_vtrace(values, returns, rewards, lam, gamma, rhos, cs)
    np.testing.assert_allclose(tgt, e_tgt, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(adv, e_adv, rtol=1e-4, atol=1e-6)


def test_mc_and_no_baseline():
    values, returns, rewards, rhos, cs, masks = _rand()
    tgt, adv = compute_target("MC", values, returns, rewards, 0.7, 0.9, rhos, cs, masks)
    np.testing.assert_allclose(tgt, returns)
    np.testing.assert_allclose(adv, returns - values)
    tgt, adv = compute_target("TD", None, returns, rewards, 0.7, 0.9, rhos, cs, masks)
    np.testing.assert_allclose(tgt, returns)
    np.testing.assert_allclose(adv, returns)


def test_mask_forces_passthrough():
    """mask=0 means lambda=1 everywhere: TD(1) == discounted Monte Carlo."""
    values, returns, rewards, rhos, cs, _ = _rand()
    masks = np.zeros((B, T, P, C), dtype=np.float32)
    tgt, _ = compute_target("TD", values, returns, rewards, 0.0, 1.0, rhos, cs, masks)
    # pure MC rollup of rewards to the bootstrap
    expect = np.zeros_like(values)
    expect[:, -1] = returns[:, -1]
    for i in range(T - 2, -1, -1):
        expect[:, i] = rewards[:, i] + expect[:, i + 1]
    np.testing.assert_allclose(tgt, expect, rtol=1e-5)


def test_vtrace_reduces_to_td_when_onpolicy():
    """With rho=c=1, full masks, gamma=1 and a zero terminal reward, the
    V-Trace correction collapses to the TD(lambda) backup (both become
    V_i + sum (gamma*lambda)^j delta_{i+j} with identical boundary)."""
    values, returns, rewards, _, _, _ = _rand()
    rewards = rewards.copy()
    rewards[:, -1] = 0.0
    ones = np.ones((B, T, P, C), dtype=np.float32)
    vt, _ = compute_target("VTRACE", values, returns, rewards, 0.7, 1.0, ones, ones, ones)
    td, _ = compute_target("TD", values, returns, rewards, 0.7, 1.0, ones, ones, ones)
    np.testing.assert_allclose(vt, td, rtol=1e-4, atol=1e-5)
