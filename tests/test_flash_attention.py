"""Pallas flash-attention kernel tests (interpreter backend on CPU).

Golden-checked against the fp32 XLA reference for causal and full
attention, odd head dims (lane padding), bf16 inputs, and gradients
through the custom VJP.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu.ops.flash_attention import flash_attention
from handyrl_tpu.ops.ring_attention import full_attention_reference as _reference


def _qkv(seed, B, T, H, D, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, T, H, D), jnp.float32).astype(dtype)
    return mk(kq), mk(kk), mk(kv)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(2, 128, 2, 16), (1, 256, 4, 64)])
def test_flash_matches_reference(causal, shape):
    q, k, v = _qkv(0, *shape)
    out = flash_attention(q, k, v, causal)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_bf16():
    q, k, v = _qkv(1, 2, 128, 2, 32, jnp.bfloat16)
    out = flash_attention(q, k, v, True)
    ref = _reference(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


def test_flash_gradients():
    q, k, v = _qkv(2, 1, 128, 2, 16)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_rejects_ragged_tiles():
    q, k, v = _qkv(3, 1, 100, 2, 16)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, 64, 64)
