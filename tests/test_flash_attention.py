"""Pallas flash-attention kernel tests (interpreter backend on CPU).

Golden-checked against the fp32 XLA reference for causal and full
attention, odd head dims (lane padding), bf16 inputs, and gradients
through the custom VJP.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from handyrl_tpu.ops.flash_attention import flash_attention
from handyrl_tpu.ops.ring_attention import full_attention_reference as _reference


def _qkv(seed, B, T, H, D, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, T, H, D), jnp.float32).astype(dtype)
    return mk(kq), mk(kk), mk(kv)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(2, 128, 2, 16), (1, 256, 4, 64)])
def test_flash_matches_reference(causal, shape):
    q, k, v = _qkv(0, *shape)
    out = flash_attention(q, k, v, causal)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_bf16():
    q, k, v = _qkv(1, 2, 128, 2, 32, jnp.bfloat16)
    out = flash_attention(q, k, v, True)
    ref = _reference(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


def test_flash_gradients():
    q, k, v = _qkv(2, 1, 128, 2, 16)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_rejects_ragged_tiles():
    q, k, v = _qkv(3, 1, 100, 2, 16)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, 64, 64)


# -- masked production kernel (transformer seq-mode semantics) --------------

from handyrl_tpu.ops.flash_attention import (  # noqa: E402
    masked_attention_reference,
    masked_flash_attention,
)


def _masked_case(seed, B, T, H, D, observed_frac=1.0):
    q, k, v = _qkv(seed, B, T, H, D)
    km = jax.random.uniform(jax.random.PRNGKey(seed + 100), (B, T))
    key_mask = (km < observed_frac).astype(jnp.float32)
    slopes = 2.0 ** (-jnp.arange(1, H + 1, dtype=jnp.float32))
    return q, k, v, key_mask, slopes


@pytest.mark.parametrize(
    "T,window,observed_frac",
    [
        (128, 1 << 30, 1.0),   # tile-aligned, no eviction, fully observed
        (128, 8, 0.7),         # ring eviction + sparse observation masks
        (100, 16, 0.7),        # ragged T exercises the internal padding
    ],
)
def test_masked_flash_matches_reference(T, window, observed_frac):
    """The DEFAULT TPU seq-attention path (train_args.seq_attention 'auto')
    vs the exact einsum the transformer einsum branch executes."""
    q, k, v, key_mask, slopes = _masked_case(7, 2, T, 2, 16, observed_frac)
    out = masked_flash_attention(q, k, v, key_mask, slopes, window=window)
    ref = masked_attention_reference(q, k, v, key_mask, slopes, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_masked_flash_gradients():
    """Chunked-recompute custom VJP vs autodiff of the einsum reference."""
    q, k, v, key_mask, slopes = _masked_case(9, 1, 128, 2, 16, 0.8)

    def loss_flash(q, k, v):
        return (masked_flash_attention(q, k, v, key_mask, slopes, window=8) ** 2).sum()

    def loss_ref(q, k, v):
        return (masked_attention_reference(q, k, v, key_mask, slopes, window=8) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize(
    "T,window",
    [
        (512, 200),
        # the T1024 point rides the slow leg: interpret-mode kernel cost
        # grows with the tile grid, and the T512 point already exercises
        # every code path (multi-tile grid, eviction window, ragged mask)
        pytest.param(1024, 384, marks=pytest.mark.slow),
    ],
)
def test_masked_flash_long_window_golden(T, window):
    """The production long-context configuration — T512/T1024 windows,
    ragged observation masks, ALiBi slopes, a non-default eviction window
    — forward AND custom-VJP gradients vs the exact einsum reference
    (interpret-mode kernel on CPU).  This is the shape regime the
    transformer_long bench drives on-chip; the golden pin here keeps the
    kernel exact where it is about to be trusted for training."""
    q, k, v, key_mask, slopes = _masked_case(13 + T % 7, 1, T, 2, 16, 0.7)

    out = masked_flash_attention(q, k, v, key_mask, slopes, window=window)
    ref = masked_attention_reference(q, k, v, key_mask, slopes, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        return (
            masked_flash_attention(q, k, v, key_mask, slopes, window=window) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            masked_attention_reference(q, k, v, key_mask, slopes, window=window) ** 2
        ).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_masked_flash_custom_blocks():
    """blk_q/blk_k are config knobs now (train_args.blk_q/blk_k): a
    non-default tiling must compute the identical function, including at
    block sizes that force multi-tile grids and padded windows."""
    q, k, v, key_mask, slopes = _masked_case(21, 2, 192, 2, 16, 0.8)
    ref = masked_attention_reference(q, k, v, key_mask, slopes, window=24)
    for blk_q, blk_k in ((32, 64), (64, 32), (128, 128)):
        out = masked_flash_attention(
            q, k, v, key_mask, slopes, window=24, blk_q=blk_q, blk_k=blk_k
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"blk_q={blk_q} blk_k={blk_k}",
        )


def test_effective_blocks_single_source_of_truth():
    from handyrl_tpu.ops.flash_attention import effective_blocks

    assert effective_blocks(100, 128, 128) == (128, 128, 128)
    assert effective_blocks(192, 64, 32) == (64, 32, 192)
    assert effective_blocks(8, 256, 256) == (128, 128, 128)
    for T in (8, 100, 512, 1000):
        bq, bk, Tp = effective_blocks(T, 64, 128)
        assert Tp % bq == 0 and Tp % bk == 0 and Tp >= T


def test_masked_flash_bf16():
    """compute_dtype=bfloat16 sends bf16 q/k/v through the masked kernel;
    scores accumulate fp32 either way, so outputs track the fp32 einsum
    reference within bf16 rounding."""
    q, k, v, key_mask, slopes = _masked_case(11, 2, 128, 2, 16, 0.8)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = masked_flash_attention(qb, kb, vb, key_mask, slopes, window=8)
    ref = masked_attention_reference(q, k, v, key_mask, slopes, window=8)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=3e-2, atol=3e-2
    )
